"""Observability: structured telemetry for the pipelined trainer.

Three pillars (docs/OBSERVABILITY.md):

  schema.py   versioned record schema (run header / epoch / eval /
              summary) + validation — the stable contract bench.py,
              scripts/*.py and the report CLI consume
  metrics.py  MetricsLogger, the JSONL event sink, plus host probes
              (device_info / mesh_info / memory_snapshot)
  trace.py    XLA trace annotations (named_phase for traced code,
              trace_span for host spans) and PhaseTimer — the
              exception-safe, nesting-aware generalization of the
              reference-parity CommTimer (utils/timer.py is now a shim
              over it)
  format.py   the canonical log-line formatters; the reference-format
              lines (train.py:369-371, :33-39, :54-60) are pinned
              byte-exact by tests/test_obs.py
  hw.py       public per-chip peak-FLOPs table (MFU reporting)
  profiler.py device-trace profiling windows: fold a jax.profiler
              capture into MEASURED per-phase device time + the
              comm/compute overlap fraction (docs/OBSERVABILITY.md
              "Profiling")
  anatomy.py  compiled-step anatomy: per-phase FLOP/byte attribution
              from the optimized HLO + the on-chip ablation clock
  timeline.py cross-rank Perfetto/Chrome-trace timelines from merged
              metrics JSONL streams (cli/timeline.py is the CLI)
  live.py     live telemetry plane: tail-following stream discovery
              + the rolling LiveAggregator behind cli/monitor.py
  health.py   SLO alert rules, the Prometheus /metrics + /health
              exporters, and the MonitorServer HTTP endpoint
  trend.py    bench trend tracking over BENCH_r*.json /
              MULTICHIP_*.json with best-known-headline regression
              flags (scripts/bench_trend.py is the CLI)
  flight.py   black-box flight recorder: bounded breadcrumb ring,
              atomic blackbox-r<k>.json crash dumps, faulthandler
              all-thread stack capture, sub-watchdog stall detector
  postmortem.py  automated root-cause diagnosis: bundles blackbox
              dumps + stream tails + ledgers, runs the ordered
              evidence-citing rule set to a ranked verdict
              (cli/debug.py is the `pipegcn-debug explain` CLI)

The reporting CLI lives in cli/report.py (`python -m
pipegcn_tpu.cli.report metrics.jsonl`); the timeline CLI in
cli/timeline.py (`python -m pipegcn_tpu.cli.timeline r0.jsonl ...`).

No reference counterpart: the reference's only telemetry is stdout
print lines and the result txt files; this subsystem is the
machine-readable record every perf claim reports through.
"""

from .flight import (
    FlightRecorder,
    StallDetector,
    capture_stacks,
    dump_blackbox,
    get_recorder,
)
from .format import epoch_line, reference_eval_line, reference_train_line
from .live import (
    LiveAggregator,
    discover_streams,
    merge_streams,
    read_stream,
)
from .metrics import (
    MetricsLogger,
    device_info,
    memory_snapshot,
    mesh_info,
    read_metrics,
)
from .schema import (
    ALERT_FIELDS,
    ANATOMY_FIELDS,
    BLACKBOX_FIELDS,
    DIAGNOSIS_FIELDS,
    EPOCH_FIELDS,
    EVAL_FIELDS,
    FAULT_FIELDS,
    PROFILE_FIELDS,
    RECOVERY_FIELDS,
    RUN_FIELDS,
    SCHEMA_VERSION,
    SPAN_FIELDS,
    STALENESS_FIELDS,
    SUMMARY_FIELDS,
    validate_record,
)
from .trace import PhaseTimer, named_phase, trace_span

__all__ = [
    "SCHEMA_VERSION",
    "RUN_FIELDS",
    "EPOCH_FIELDS",
    "EVAL_FIELDS",
    "SUMMARY_FIELDS",
    "FAULT_FIELDS",
    "RECOVERY_FIELDS",
    "PROFILE_FIELDS",
    "ANATOMY_FIELDS",
    "STALENESS_FIELDS",
    "ALERT_FIELDS",
    "SPAN_FIELDS",
    "BLACKBOX_FIELDS",
    "DIAGNOSIS_FIELDS",
    "validate_record",
    "FlightRecorder",
    "StallDetector",
    "capture_stacks",
    "dump_blackbox",
    "get_recorder",
    "LiveAggregator",
    "discover_streams",
    "merge_streams",
    "read_stream",
    "MetricsLogger",
    "read_metrics",
    "device_info",
    "mesh_info",
    "memory_snapshot",
    "PhaseTimer",
    "named_phase",
    "trace_span",
    "epoch_line",
    "reference_train_line",
    "reference_eval_line",
]
