"""MetricsLogger — the JSONL event sink — and host-side probes.

One line per record, appended and flushed immediately so a crashed run
still leaves every completed epoch on disk (the trainer's crash
checkpoint philosophy applied to telemetry). Values are sanitized
through `_jsonable` (numpy scalars/arrays, jnp dtypes, tuples) so
callers can pass device-adjacent objects without ceremony.

jax is imported lazily and only by the probes (device_info /
mesh_info / memory_snapshot): the logger itself must stay importable
from jax-free host processes (partition builders, report tooling).
"""

from __future__ import annotations

import collections
import json
import os
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Union

from .schema import SCHEMA_VERSION, validate_record

# ring-buffer capacity while the sink is io-degraded; beyond this the
# OLDEST buffered records are dropped (and counted in the recovery
# record) — fault/recovery records are small, so 4096 lines outlasts
# any realistic disk-full window
_RING_CAPACITY = 4096


def _storage_io():
    # lazy: resilience/__init__ -> elastic -> this module would cycle
    # on a top-level import of the storage shim
    from ..resilience.storage import FAULTY_IO
    return FAULTY_IO


def _jsonable(v: Any) -> Any:
    """Best-effort conversion to JSON-serializable types."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    # numpy / jax scalars and arrays without importing either
    item = getattr(v, "item", None)
    if callable(item) and getattr(v, "ndim", None) == 0:
        return _jsonable(v.item())
    tolist = getattr(v, "tolist", None)
    if callable(tolist):
        try:
            return _jsonable(tolist())
        except Exception:
            pass
    return str(v)


class MetricsLogger:
    """Append-only JSONL sink with schema validation.

    `path` may be a filesystem path (parent dirs created, file opened
    in append mode) or any object with ``write``. Use as a context
    manager or call :meth:`close`; a logger left open still has every
    record on disk (each write is flushed).

    Storage-fault degradation (docs/RESILIENCE.md "Storage faults"):
    when the sink's disk fails (ENOSPC, EROFS, a yanked mount — or the
    injected equivalents, resilience/storage.py) the logger goes
    *io-degraded* instead of raising or silently dropping: records
    accumulate in an in-memory ring buffer (one loud warning per
    episode), every subsequent write retries the disk, and on recovery
    the ring re-drains in order followed by a ``recovery/io-degraded``
    record counting what was re-drained and what (if anything) the
    ring had to drop. Fault/recovery records are therefore never
    silently lost — the worst case is a bounded, counted gap."""

    def __init__(self, path: Union[str, "os.PathLike", Any],
                 validate: bool = True):
        self._validate = validate
        self._owns_file = isinstance(path, (str, os.PathLike))
        if self._owns_file:
            path = os.fspath(path)
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a", encoding="utf-8")
            self.path: Optional[str] = path
        else:
            self._f = path
            self.path = None
        self.header_written = False
        self._ring: collections.deque = collections.deque(
            maxlen=_RING_CAPACITY)
        self._degraded = False
        self._dropped = 0
        self._n_records = 0

    # ---------------- degradation policy ------------------------------

    @property
    def degraded(self) -> bool:
        """True while the sink is io-degraded (records ring-buffered)."""
        return self._degraded

    def _enter_degraded(self, exc: BaseException,
                        line: Optional[str]) -> None:
        if not self._degraded:
            self._degraded = True
            warnings.warn(
                f"metrics sink {self.path or self._f!r} is io-degraded "
                f"({exc!r}); buffering records in memory (capacity "
                f"{_RING_CAPACITY}) and retrying on every write")
        if line is not None:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(line)

    def _try_recover(self) -> bool:
        """Attempt to re-drain the ring to the sink; True on success.
        The recovery record is appended DIRECTLY (not via write()) so a
        failure mid-drain can never recurse back into degradation
        bookkeeping with half the ring gone — lines leave the ring only
        after they hit the file."""
        if not self._degraded:
            return True
        try:
            if self._owns_file and getattr(self._f, "closed", False):
                if self.path is not None:
                    _storage_io().gate(self.path, "open")
                self._f = open(self.path, "a", encoding="utf-8")
            if self.path is not None:
                _storage_io().gate(self.path, "write")
            redrained = 0
            while self._ring:
                self._f.write(self._ring[0])
                self._ring.popleft()
                redrained += 1
            from ..resilience.storage import IO_DEGRADED
            self._f.write(json.dumps({
                "event": "recovery", "kind": IO_DEGRADED, "epoch": -1,
                "rank": _local_rank(), "redrained": redrained,
                "dropped": self._dropped,
                "time_unix": time.time()}) + "\n")
            self._f.flush()
        except (OSError, ValueError):
            return False
        self._degraded = False
        self._dropped = 0
        return True

    # ---------------- record writers ----------------------------------

    def write(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        rec = {k: _jsonable(v) for k, v in rec.items()}
        if self._validate:
            validate_record(rec)
        line = json.dumps(rec) + "\n"
        self._n_records += 1
        if self._degraded and not self._try_recover():
            self._enter_degraded(OSError("sink still degraded"), line)
            return rec
        try:
            if self.path is not None:
                _storage_io().gate(self.path, "write")
            self._f.write(line)
            self._f.flush()
        except OSError as exc:
            self._enter_degraded(exc, line)
        return rec

    def run_header(self, config: Optional[dict] = None,
                   device: Optional[dict] = None,
                   mesh: Optional[dict] = None, **extra) -> Dict[str, Any]:
        """The one-per-run header: schema version + what produced the
        numbers. Idempotent guard lives in `header_written` — callers
        that may be second in line (fit() after the CLI) check it."""
        rec = self.write({
            "event": "run",
            "schema_version": SCHEMA_VERSION,
            "time_unix": time.time(),
            "config": config or {},
            "device": device or {},
            "mesh": mesh or {},
            **extra,
        })
        self.header_written = True
        return rec

    def epoch(self, epoch: int, step_time_s: float, loss: float,
              grad_norm: float, halo_bytes: int, staleness_age: int,
              memory: Optional[dict] = None, **extra) -> Dict[str, Any]:
        # time_unix (record write time = dispatch end) is an optional
        # extra: the timeline CLI uses it for real wall-clock alignment
        # across ranks when every epoch record carries it
        extra.setdefault("time_unix", time.time())
        return self.write({
            "event": "epoch",
            "epoch": int(epoch),
            "step_time_s": float(step_time_s),
            "loss": float(loss),
            "grad_norm": float(grad_norm),
            "halo_bytes": int(halo_bytes),
            "staleness_age": int(staleness_age),
            "memory": memory,
            **extra,
        })

    def eval_record(self, epoch: int, eval_time_s: float, val_acc: float,
                    **extra) -> Dict[str, Any]:
        extra.setdefault("time_unix", time.time())
        return self.write({
            "event": "eval",
            "epoch": int(epoch),
            "eval_time_s": float(eval_time_s),
            "val_acc": float(val_acc),
            **extra,
        })

    def summary(self, n_epochs: int, epoch_time_s: Optional[float],
                best_val: float, **extra) -> Dict[str, Any]:
        return self.write({
            "event": "summary",
            "n_epochs": int(n_epochs),
            "epoch_time_s": (None if epoch_time_s is None
                             else float(epoch_time_s)),
            "best_val": float(best_val),
            **extra,
        })

    def fault(self, kind: str, epoch: int, rank: Optional[int] = None,
              **extra) -> Dict[str, Any]:
        """A detected fault: divergence trip, preemption request,
        injected chaos fault, corrupt checkpoint generation, cross-rank
        desync, lost peer. Extras carry the kind-specific detail
        (reason, retry, trip values, source_rank/agreed for
        consensus-driven actions). `rank` defaults to this process's
        rank so multi-host JSONL streams stay attributable when merged.

        Fault records are durability-critical — they often explain a
        death the process is about to execute via ``os._exit`` (which
        skips atexit AND io buffers) — so every fault/recovery write is
        followed by :meth:`hard_flush` (flush + fsync)."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "fault",
            "kind": str(kind),
            "epoch": int(epoch),
            "rank": _local_rank() if rank is None else int(rank),
            **extra,
        })
        self.hard_flush()
        return rec

    def recovery(self, kind: str, epoch: int, rank: Optional[int] = None,
                 **extra) -> Dict[str, Any]:
        """A completed recovery from the matching fault kind (training
        progressed past the faulted epoch, or a resume restored).
        Hard-flushed like fault records (the recovery may immediately
        precede a preemption exit)."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "recovery",
            "kind": str(kind),
            "epoch": int(epoch),
            "rank": _local_rank() if rank is None else int(rank),
            **extra,
        })
        self.hard_flush()
        return rec

    def profile(self, phases: Dict[str, float], comm_s: float,
                compute_s: float, overlap_fraction: float,
                **extra) -> Dict[str, Any]:
        """A captured profiling window's MEASURED device-time
        decomposition (obs/profiler.py): per-phase seconds + the
        comm/compute overlap fraction. Extras: epoch_start/epoch_end,
        trace_files, parser coverage counters."""
        return self.write({
            "event": "profile",
            "phases": dict(phases),
            "comm_s": float(comm_s),
            "compute_s": float(compute_s),
            "overlap_fraction": float(overlap_fraction),
            **extra,
        })

    def anatomy(self, phases: Dict[str, Any], est_flops: float,
                flops: Optional[float] = None,
                attributed_flops_fraction: Optional[float] = None,
                **extra) -> Dict[str, Any]:
        """A compiled-step anatomy (obs/anatomy.py): estimated
        FLOPs/bytes per phase + XLA's own totals."""
        return self.write({
            "event": "anatomy",
            "phases": dict(phases),
            "est_flops": float(est_flops),
            "flops": None if flops is None else float(flops),
            "attributed_flops_fraction": (
                None if attributed_flops_fraction is None
                else float(attributed_flops_fraction)),
            **extra,
        })

    def staleness(self, epoch: int, layers: Dict[str, Any],
                  max_rel_drift: float, **extra) -> Dict[str, Any]:
        """A staleness probe's per-layer relative drift between stale
        and fresh boundary features (--staleness-probe-every)."""
        return self.write({
            "event": "staleness",
            "epoch": int(epoch),
            "layers": dict(layers),
            "max_rel_drift": float(max_rel_drift),
            **extra,
        })

    def numerics(self, kind: str, epoch: int, **extra) -> Dict[str, Any]:
        """A numerics-guardrail event (resilience/numerics.py): a
        loss-scale overflow (step skipped, scale backed off), a scale
        regrowth, or a tripwire provenance record naming the phase a
        non-finite value was born in. Hard-flushed: a tripwire record
        often immediately precedes a DivergenceError exit."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "numerics",
            "kind": str(kind),
            "epoch": int(epoch),
            **extra,
        })
        self.hard_flush()
        return rec

    def fallback(self, epoch: int, from_impl: str, to_impl: str,
                 **extra) -> Dict[str, Any]:
        """A kernel-fallback-ladder downgrade: the aggregation kernel
        crashed at compile/first dispatch and the trainer rebuilt one
        rung down instead of dying. Hard-flushed — the run may still be
        about to lose the device."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "fallback",
            "epoch": int(epoch),
            "from_impl": str(from_impl),
            "to_impl": str(to_impl),
            **extra,
        })
        self.hard_flush()
        return rec

    def tuning(self, winner: Dict[str, Any], source: str,
               costs, **extra) -> Dict[str, Any]:
        """The SpMM auto-tuner's dispatch decision (ops/tuner.py +
        Trainer._resolve_auto): the winning kernel config, where the
        decision came from (artifact | live | default), and the full
        measured per-candidate cost table — the record that says WHY
        this kernel dispatches."""
        extra.setdefault("time_unix", time.time())
        return self.write({
            "event": "tuning",
            "winner": dict(winner),
            "source": str(source),
            "costs": list(costs),
            **extra,
        })

    def serving(self, window_s: float, queries: int, qps: float,
                batch_fill: Optional[float], queue_depth: int,
                p50_ms: Optional[float], p95_ms: Optional[float],
                p99_ms: Optional[float],
                cache_hit_rate: Optional[float], staleness_age: int,
                shed: int = 0, param_generation: int = -1,
                param_staleness: int = 0, **extra) -> Dict[str, Any]:
        """One serving report window (serve/loadgen.run_serving_loop):
        QPS, batch fill, queue depth, latency percentiles, cache hit
        rate, the max served staleness age, plus (v7) the load-shed
        row count and the parameter-staleness axis (checkpoint
        generation served / newer generations published but not yet
        swapped in). Hard-flushed — the shutdown path's final record
        (extra ``final: true``) must survive a SIGTERM'd load
        generator (scripts/chaos.sh serving lane asserts exactly
        this)."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "serving",
            "window_s": float(window_s),
            "queries": int(queries),
            "qps": float(qps),
            "batch_fill": None if batch_fill is None else float(batch_fill),
            "queue_depth": int(queue_depth),
            "p50_ms": None if p50_ms is None else float(p50_ms),
            "p95_ms": None if p95_ms is None else float(p95_ms),
            "p99_ms": None if p99_ms is None else float(p99_ms),
            "cache_hit_rate": (None if cache_hit_rate is None
                               else float(cache_hit_rate)),
            "staleness_age": int(staleness_age),
            "shed": int(shed),
            "param_generation": int(param_generation),
            "param_staleness": int(param_staleness),
            **extra,
        })
        self.hard_flush()
        return rec

    def fleet(self, kind: str, replica: int, window: int = -1,
              **extra) -> Dict[str, Any]:
        """One serving-fleet lifecycle event (serve/fleet.py): replica
        death / failover / relaunch / rejoin, a zero-downtime checkpoint
        hot-swap, or a supervisor stop. `window` is the serving report
        window index the event fell in (-1 outside the load loop).
        Hard-flushed — replica-dead records often immediately precede
        more dying."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "fleet",
            "kind": str(kind),
            "replica": int(replica),
            "window": int(window),
            **extra,
        })
        self.hard_flush()
        return rec

    def autoscale(self, action: str, reason: str, window: int,
                  n_replicas: int, target: int,
                  evidence: Dict[str, Any], **extra) -> Dict[str, Any]:
        """One autoscaler decision (serve/autoscale.py): an executed
        scale-up/scale-down proposal or a brake refusal, with the
        triggering telemetry snapshot as evidence. Hard-flushed — the
        decision ledger is what the soak harness's replica-trajectory
        invariant replays, so it must survive a crash mid-scale."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "autoscale",
            "action": str(action),
            "reason": str(reason),
            "window": int(window),
            "n_replicas": int(n_replicas),
            "target": int(target),
            "evidence": dict(evidence),
            **extra,
        })
        self.hard_flush()
        return rec

    def stream(self, epoch: int, seq: int, edges_added: int,
               edges_deleted: int, nodes_added: int, patch_ms: float,
               tables_rebuilt: int, repadded: bool,
               slack_remaining: Dict[str, Any],
               drift: Optional[float] = None, **extra) -> Dict[str, Any]:
        """One applied graph delta batch (stream/, docs/STREAMING.md):
        what changed, what the incremental patch cost, how much of the
        reserved slack survives, and the forced probe's drift across
        the first post-patch step. Hard-flushed — a delta that blows
        the slack may be the last thing the run does, and the record
        explaining the re-pad must be on disk."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "stream",
            "epoch": int(epoch),
            "seq": int(seq),
            "edges_added": int(edges_added),
            "edges_deleted": int(edges_deleted),
            "nodes_added": int(nodes_added),
            "patch_ms": float(patch_ms),
            "tables_rebuilt": int(tables_rebuilt),
            "repadded": bool(repadded),
            "slack_remaining": dict(slack_remaining),
            "drift": None if drift is None else float(drift),
            **extra,
        })
        self.hard_flush()
        return rec

    def journal(self, op: str, seq: int, topo_generation: int,
                n_records: int = 0, source: str = "trainer",
                **extra) -> Dict[str, Any]:
        """One write-ahead delta-journal lifecycle event
        (stream/journal.py, docs/STREAMING.md "Durability & replay"):
        append/watermark from the trainer's stream boundary,
        replay/truncate/verify from a resume, degraded/recovered from
        the journal's own pending queue, skew from the router.
        Hard-flushed — the journal records ARE the durability audit
        trail, so they must survive the very crash they describe."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "journal",
            "op": str(op),
            "seq": int(seq),
            "topo_generation": int(topo_generation),
            "n_records": int(n_records),
            "source": str(source),
            **extra,
        })
        self.hard_flush()
        return rec

    def membership(self, generation: int, assignment: Dict[str, Any],
                   trigger: str,
                   restart_latency_s: Optional[float] = None,
                   **extra) -> Dict[str, Any]:
        """One elastic membership generation (resilience/elastic.py):
        who owns which partitions and why the fleet was (re)launched.
        `assignment` is Assignment.as_json(); restart_latency_s is the
        death-detect -> relaunch wall time (None on the initial
        launch). Hard-flushed — the supervisor may be SIGKILL'd
        between generations and the ledger/metrics must never
        disagree about how far membership advanced."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "membership",
            "generation": int(generation),
            "assignment": dict(assignment),
            "trigger": str(trigger),
            "restart_latency_s": (None if restart_latency_s is None
                                  else float(restart_latency_s)),
            **extra,
        })
        self.hard_flush()
        return rec

    def soak(self, episode: int, seed: int, schedule: Sequence[str],
             invariants: Dict[str, Any], verdict: str,
             **extra) -> Dict[str, Any]:
        """One chaos-soak episode verdict (resilience/soak.py): the
        composed fault schedule and the per-invariant results. Hard-
        flushed — a red verdict must survive even if the soak driver
        itself dies right after."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "soak",
            "episode": int(episode),
            "seed": int(seed),
            "schedule": list(schedule),
            "invariants": dict(invariants),
            "verdict": str(verdict),
            **extra,
        })
        self.hard_flush()
        return rec

    def alert(self, rule: str, state: str, severity: str, source: str,
              value: Optional[float], threshold: Optional[float],
              message: str, **extra) -> Dict[str, Any]:
        """One SLO alert edge (obs/health.py rule engine): state "fire"
        when the rule's predicate first holds, "resolve" when it first
        stops. Hard-flushed — an alert often describes a run that is
        about to get worse, and the operator trail must survive the
        monitor dying with it."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "alert",
            "rule": str(rule),
            "state": str(state),
            "severity": str(severity),
            "source": str(source),
            "value": None if value is None else float(value),
            "threshold": None if threshold is None else float(threshold),
            "message": str(message),
            **extra,
        })
        self.hard_flush()
        return rec

    def span(self, trace_id: str, span_id: str, op: str, t_start: float,
             dur_ms: float, status: str = "ok", **extra) -> Dict[str, Any]:
        """One sampled serving-path span (docs/SERVING.md tracing):
        queue/dispatch/shed on the driver, rpc/replica/engine across
        the fleet hop. NOT hard-flushed — spans are high-volume and
        advisory; the flush-per-write default already lands them."""
        return self.write({
            "event": "span",
            "trace_id": str(trace_id),
            "span_id": str(span_id),
            "op": str(op),
            "t_start": float(t_start),
            "dur_ms": float(dur_ms),
            "status": str(status),
            **extra,
        })

    def tracesync(self, rank: int, epoch: int, t_anchor: float,
                  generation: int = 0, **extra) -> Dict[str, Any]:
        """One training clock anchor (obs/trainspan.py): this rank's
        wall-clock reading of the dispatched block's harvest barrier.
        NOT hard-flushed — same volume/durability class as spans (one
        per dispatched block; the flush-per-write default lands them,
        and every fault path hard-flushes the whole sink anyway)."""
        return self.write({
            "event": "tracesync",
            "rank": int(rank),
            "epoch": int(epoch),
            "t_anchor": float(t_anchor),
            "generation": int(generation),
            **extra,
        })

    def blackbox(self, rank: int, reason: str,
                 crumbs: Sequence[Dict[str, Any]],
                 last_crumb: Optional[Dict[str, Any]],
                 open_spans: Sequence[Dict[str, Any]],
                 stacks: Optional[str] = None,
                 **extra) -> Dict[str, Any]:
        """One flight-recorder dump mirrored into the metrics stream
        (obs/flight.py writes the authoritative blackbox-r<k>.json
        itself; this record makes the dump discoverable through the
        same stream tail every other consumer follows). Hard-flushed —
        by definition the process is dying or wedged when one of these
        is written."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "blackbox",
            "rank": int(rank),
            "reason": str(reason),
            "crumbs": list(crumbs),
            "last_crumb": (None if last_crumb is None
                           else dict(last_crumb)),
            "open_spans": list(open_spans),
            "stacks": None if stacks is None else str(stacks),
            **extra,
        })
        self.hard_flush()
        return rec

    def diagnosis(self, verdict: str, confidence: float,
                  evidence: Sequence[str], remediation: str,
                  deterministic: bool, **extra) -> Dict[str, Any]:
        """One postmortem verdict (obs/postmortem.py): the rule
        engine's confidence-ranked root cause with its citing
        evidence. Hard-flushed — the supervisor's fail-fast decision
        rides on this record and must never be lost to a crash."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "diagnosis",
            "verdict": str(verdict),
            "confidence": float(confidence),
            "evidence": [str(e) for e in evidence],
            "remediation": str(remediation),
            "deterministic": bool(deterministic),
            **extra,
        })
        self.hard_flush()
        return rec

    def integrity(self, epoch: int, check: str, outcome: str,
                  target: Optional[str], cadence: int,
                  overhead_s: float, **extra) -> Dict[str, Any]:
        """One SDC-detector verdict (resilience/integrity.py): a
        digest scrub, Freivalds compute verification, or halo wire
        checksum outcome at a check boundary. Mismatch records are
        hard-flushed (the run may be about to roll back or quarantine
        itself); ok records take the ordinary flush-per-write path —
        they are cadence-periodic bookkeeping, not last words."""
        extra.setdefault("time_unix", time.time())
        rec = self.write({
            "event": "integrity",
            "epoch": int(epoch),
            "check": str(check),
            "outcome": str(outcome),
            "target": None if target is None else str(target),
            "cadence": int(cadence),
            "overhead_s": float(overhead_s),
            **extra,
        })
        if outcome != "ok":
            self.hard_flush()
        return rec

    def event(self, event: str, **fields) -> Dict[str, Any]:
        """Free-form record (e.g. bench headline, rank progress) — only
        the ``event`` discriminator is contracted."""
        return self.write({"event": event, **fields})

    def stats(self) -> Dict[str, Any]:
        """Sink health counters for the live exporter (docs/
        OBSERVABILITY.md "Live monitoring"): records accepted since
        open, the PR-14 io-degraded state, the ring-buffer depth, and
        how many buffered records the ring has had to drop. Cheap and
        side-effect free — safe to poll from a monitor thread."""
        return {
            "records": self._n_records,
            "degraded": self._degraded,
            "ring_depth": len(self._ring),
            "dropped": self._dropped,
        }

    # ---------------- lifecycle ---------------------------------------

    def hard_flush(self) -> None:
        """Flush AND fsync: records survive even an ``os._exit`` (which
        skips atexit handlers and io teardown) or a SIGKILL an instant
        later. Call before every hard-exit / crash-checkpoint path;
        fault/recovery writers call it automatically. Best-effort on
        sinks without a file descriptor (StringIO tests); a DISK
        failure here enters io-degraded instead of being swallowed —
        the records this method exists to make durable are exactly the
        ones that must not vanish without a trace."""
        if self._degraded and not self._try_recover():
            return
        try:
            self._f.flush()
        except ValueError:
            return  # closed/detached sink: nothing to make durable
        except OSError as exc:
            self._enter_degraded(exc, None)
            return
        try:
            # isolated: io.UnsupportedOperation (StringIO sinks) is BOTH
            # an OSError and a ValueError — a missing fd means "nothing
            # to fsync", never "the disk failed"
            fd = self._f.fileno()
        except (AttributeError, OSError, ValueError):
            return
        try:
            if self.path is not None:
                _storage_io().gate(self.path, "fsync")
            os.fsync(fd)
        except OSError as exc:
            self._enter_degraded(exc, None)

    def close(self) -> None:
        if self._degraded:
            self._try_recover()
        if self._degraded and (self._ring or self._dropped):
            warnings.warn(
                f"metrics sink {self.path or self._f!r} closed while "
                f"io-degraded: {len(self._ring)} buffered and "
                f"{self._dropped} dropped records were lost")
        if self._owns_file and not self._f.closed:
            try:
                self._f.close()
            except OSError:
                pass  # close-flush of a dead disk; the ring warning
                # above already reported the loss

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_metrics(path: Union[str, "os.PathLike"]) -> List[Dict[str, Any]]:
    """Parse a metrics JSONL file; skips blank lines, raises on a
    malformed one (a torn final line from a killed run is reported with
    its line number rather than silently dropped)."""
    out = []
    with open(os.fspath(path), encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError as exc:
                raise ValueError(f"{path}:{i}: malformed JSONL line "
                                 f"({exc})") from exc
    return out


# ---------------- host probes (lazy jax) ------------------------------


def _local_rank() -> int:
    """This process's rank (jax.process_index) for fault/recovery
    attribution; 0 in jax-free or uninitialized-backend contexts so the
    logger itself stays importable without jax."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def device_info() -> Dict[str, Any]:
    """Backend identity for the run header; {} when jax has no
    initialized backend (pure-host tooling)."""
    try:
        import jax

        d = jax.local_devices()[0]
        return {
            "platform": d.platform,
            "device_kind": d.device_kind,
            "n_devices": jax.device_count(),
            "n_local_devices": jax.local_device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
    except Exception:
        return {}


def mesh_info(mesh) -> Dict[str, Any]:
    """Axis names/sizes of a jax.sharding.Mesh (header `mesh` field)."""
    try:
        return {
            "axis_names": list(mesh.axis_names),
            "shape": {str(k): int(v) for k, v in
                      dict(mesh.shape).items()},
            "n_devices": int(len(mesh.devices.flat)),
        }
    except Exception:
        return {}


def memory_snapshot() -> Dict[str, Any]:
    """HBM watermarks of local device 0 (`memory_stats()`), with the
    keys always present: platforms without allocator stats (CPU) report
    nulls so epoch records keep a stable shape."""
    stats = None
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if not stats:
        return {"bytes_in_use": None, "peak_bytes_in_use": None,
                "bytes_limit": None}
    return {
        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
        "peak_bytes_in_use": int(stats.get(
            "peak_bytes_in_use", stats.get("bytes_in_use", 0))),
        "bytes_limit": (int(stats["bytes_limit"])
                        if "bytes_limit" in stats else None),
    }
