"""PipeGCN-TPU: a TPU-native framework for full-graph GNN training with
pipelined boundary-node communication.

Re-implements the capabilities of PipeGCN (ICLR 2022) — METIS-style graph
partitioning across devices, per-layer halo (boundary node) feature exchange,
cross-epoch pipelining of that exchange (staleness-1), optional smoothing
corrections, and asynchronous gradient reduction — as a single SPMD JAX
program over a `jax.sharding.Mesh`, instead of one Python process per
partition with gloo p2p (reference: /root/reference/main.py:44-59,
helper/feature_buffer.py).

Layout:
    graph/      host-side graph containers + dataset loaders (numpy)
    partition/  graph partitioner + halo index pipeline (host, numpy)
    ops/        TPU compute kernels (XLA/bucket/block SpMM + auto-tuner)
    models/     GraphSAGE model family (pure JAX, functional params)
    parallel/   mesh, halo exchange, pipelining, gradient reduction, SyncBN
    train/      trainer, losses, metrics, evaluation
    utils/      timers, logging, checkpointing, config
"""

__version__ = "0.1.0"
