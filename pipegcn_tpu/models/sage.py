"""GraphSAGE model family — pure-JAX functional implementation.

Behavioral parity with the reference (module/model.py:25-58,
module/layer.py:8-62, module/sync_bn.py:7-56), re-architected for TPU:
parameters are explicit pytrees, communication is an injected callback
(`comm_update`) instead of a process-global buffer singleton
(reference helper/context.py:4-5), and distributed normalization takes an
injected `psum` so the same code runs single-device (psum = identity) and
inside `shard_map` (psum over the mesh axis).

Layer stack (reference module/model.py:29-38): `n_layers - n_linear`
graph layers followed by `n_linear` plain dense layers; LayerNorm or
SyncBatchNorm + activation between all but the last layer. Per-layer
training order (module/model.py:43-57): comm update -> dropout -> layer
-> norm -> activation.

Graph layer semantics (module/layer.py:40-62):
  training:  ah = spmm(fbuf)/in_deg;  h = fbuf[:n_dst] @ W1 + ah @ W2 (+b)
             (first layer under use_pp: h = fbuf @ W (+b), input is the
             precomputed [feat, mean-neighbor-feat] concat of width 2F)
  eval:      same weights on a full homogeneous graph, degrees from the
             graph itself; use_pp layer computes concat(feat, ah) @ W.

Init (module/layer.py:24-36): U(-1/sqrt(fan_in), +1/sqrt(fan_in)) for all
weights and biases (the dense tail's torch default has the same bounds).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops.spmm import spmm_mean

Params = dict
PsumFn = Callable[[jax.Array], jax.Array]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    layer_sizes: Tuple[int, ...]   # [in_feat, hidden..., n_class]
    # 'graphsage' (reference parity, module/layer.py) | 'gcn' | 'gat'
    # (framework extensions). GCN: symmetric-normalized convolution,
    # h_i = W Σ_j h_j / sqrt(d_i d_j) with the self-loop already in the
    # finalized graph; reuses every aggregation kernel unchanged — the
    # src-side 1/sqrt(d) scaling happens on the owner BEFORE the halo
    # exchange, the dst side folds into the mean kernel's output
    # (mean * sqrt(d)). GAT: multi-head edge-softmax attention
    # (n_heads); runs on the raw-edge formulation (attention weights
    # are per-edge, so the precomputed unweighted kernel tables do not
    # apply); halo sources attend with their (possibly stale) features,
    # exactly the staleness semantics of the mean path.
    model: str = "graphsage"
    n_heads: int = 4               # GAT attention heads
    leaky_slope: float = 0.2       # GAT LeakyReLU slope
    n_linear: int = 0              # dense tail layers (Yelp uses 2)
    use_pp: bool = False
    norm: Optional[str] = "layer"  # 'layer' | 'batch' | None
    dropout: float = 0.5
    train_size: int = 0            # global n_train (SyncBN divisor, loss)
    spmm_chunk: Optional[int] = None
    sorted_edges: bool = False     # edge_dst ascending (CSR order)
    # 'xla' | 'bucket' | 'block' | 'auto' — must stay in sync with
    # cli/parser.py --spmm-impl and Trainer._setup_spmm; 'auto'
    # resolves from the measured tuning table (ops/tuner.py)
    spmm_impl: str = "xla"
    block_tile: int = 256          # dense-tile edge for spmm_impl='block'
    # minimum edges for a (dst, src) tile to go dense; None = the
    # read-cost break-even tile*tile/n_feat (block_spmm.BlockPlan)
    block_nnz: Optional[int] = None
    # union-gather group size for the block kernel's dense path: that
    # many CONSECUTIVE dst tiles share one gathered source-tile union
    # (block_spmm._group_union; measured F-tile dedupe headroom in
    # docs/PERF_NOTES.md). 1 = per-tile K-class layout
    block_group: int = 1
    # bucket-merge lever (ops/bucket_spmm._bucket_widths min_width):
    # buckets narrower than this merge into the first surviving ladder
    # rung, trading bounded padding for fewer per-bucket gather
    # launches/transients. 0 = full ladder.
    bucket_merge: int = 0
    # spmm_impl='auto' resolution (ops/tuner.py): True lets a cache
    # miss run the live micro-benchmark campaign; False restricts auto
    # to a persisted tuning table (falling back to the deterministic
    # default kernel when none exists — never a live measurement)
    tune: bool = True
    # edge budget of the tuner's sampled degree-distribution slice
    tuner_samples: int = 200_000
    # gather-transport dtype for the bucket kernel / block remainder /
    # GAT attention kernel's wide value+cotangent gathers
    # (bucket_spmm.transport_dtypes): None = activation dtype;
    # 'float8' = e4m3 activations / e5m2 cotangents — halves gathered
    # rows at F=256 (the gather path is request-rate-bound at 256-byte
    # rows); accumulation stays f32. Casts SATURATE at the fp8 finite
    # max (transport_cast), so raw layer-0 features beyond +-448
    # (use_pp=False / gcn) clamp instead of going NaN; the one-shot
    # metric-bearing paths (pp precompute, sharded eval) are exempt.
    rem_dtype: Optional[str] = None
    # amax-clamped fp8 transport (resilience/numerics guardrail): scale
    # each gathered tensor by a power of two derived from its running
    # amax so the cast lands mid-range in e4m3/e5m2 instead of
    # saturating (or flushing to zero) at the static clamp; the inverse
    # scale is applied after the (linear) aggregation. No-op unless
    # rem_dtype is 'float8'.
    rem_amax: bool = False
    # dropout mask generation width (the RNG floor lever, with
    # --rng-impl): 32 = jax.random.bernoulli (uniform f32 compare,
    # reference parity); 8 = one random BYTE per element compared
    # against round(rate*256) — a quarter of the generated bits and no
    # f32 conversion, at the cost of quantizing the keep probability to
    # 1/256 (invisible at the usual 0.5). Masks differ from 32-bit mode
    # at the same seed (equally valid dropout noise).
    dropout_bits: int = 32
    # slab-gather streaming plans (ops/bucket_spmm.build_slab_plan):
    # 'on' rewrites contiguous gather-index runs in the bucket/block-
    # remainder tables into dynamic_slice streaming copies (pays off
    # only on reordered layouts where runs exist), 'off' keeps plain
    # clipped-take gathers, 'auto' defers to the tuner's measured
    # reorder x slab winner (ops/tuner.py candidate_grid).
    slab: str = "auto"
    # lane-pad the input feature slab to the TPU 128-lane boundary:
    # the trainer appends zero columns on the feature axis and rewrites
    # layer_sizes[0] to the padded width, so the per-epoch HBM feature
    # reads (and the slab-gather dynamic_slice copies) move whole
    # (8, 128) tiles instead of ragged rows. Zero columns contribute
    # nothing to any matmul, so outputs are unchanged; only the layer-0
    # weight init draw differs (different shape, different RNG stream).
    lane_pad: bool = False
    dtype: str = "float32"         # compute dtype: 'float32' | 'bfloat16'

    def __post_init__(self):
        if self.model not in ("graphsage", "gcn", "gat"):
            raise ValueError(f"unknown model: {self.model}")
        if self.rem_dtype in ("", "none"):
            # ONE sentinel: every consumer sees None for "no transport
            # narrowing" (CLI/bench pass their 'none' strings through)
            object.__setattr__(self, "rem_dtype", None)
        if self.rem_dtype not in (None, "float8", "bfloat16"):
            raise ValueError(
                f"unknown rem_dtype: {self.rem_dtype!r} "
                "(none | bfloat16 | float8)")
        if self.bucket_merge < 0:
            raise ValueError(
                f"bucket_merge must be >= 0, got {self.bucket_merge}")
        if self.dropout_bits not in (8, 32):
            raise ValueError(
                f"dropout_bits must be 8 or 32, got {self.dropout_bits}")
        if self.slab not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown slab mode: {self.slab!r} (auto | on | off)")
        if self.model in ("gcn", "gat") and self.use_pp:
            # the pp precompute caches SAGE's mean-neighbor concat;
            # gcn/gat first layers aggregate like every other layer
            raise ValueError("use_pp is a GraphSAGE-only optimization")
        if self.model == "gat":
            if self.n_heads < 1:
                raise ValueError(f"n_heads must be >= 1, got "
                                 f"{self.n_heads}")
            if self.spmm_impl not in ("xla", "auto", "bucket"):
                # per-edge attention weights need the attention-bucket
                # kernel (ops/gat_bucket.py); the block tables
                # are unweighted and cannot express them
                raise ValueError(
                    f"spmm_impl={self.spmm_impl!r} does not apply to "
                    f"gat; use 'xla', 'bucket' or 'auto'")
            for i in range(self.n_layers - self.n_linear):
                if i < self.n_layers - 1 \
                        and self.layer_sizes[i + 1] % self.n_heads:
                    raise ValueError(
                        f"gat hidden width {self.layer_sizes[i + 1]} not "
                        f"divisible by n_heads={self.n_heads}")

    @property
    def n_layers(self) -> int:
        return len(self.layer_sizes) - 1

    @property
    def n_graph_layers(self) -> int:
        return self.n_layers - self.n_linear

    @property
    def compute_dtype(self):
        """Mixed precision, TPU style: activations, halo transport and
        SpMM messages flow in bfloat16 (halving HBM gather traffic and
        ICI volume; MXU-native matmuls); parameters, optimizer state,
        normalization statistics, SpMM accumulation and the loss stay
        float32. The reference has no analogue (torch fp32 throughout);
        dtype='float32' reproduces that exactly."""
        if self.dtype == "bfloat16":
            return jnp.bfloat16
        if self.dtype == "float32":
            return jnp.float32
        raise ValueError(f"unknown dtype: {self.dtype}")


def _uniform(rng, shape, bound):
    return jax.random.uniform(
        rng, shape, minval=-bound, maxval=bound, dtype=jnp.float32
    )


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Parameter pytree: {'layers': [...], 'norms': [...]}.

    Graph layers hold {'w1','b1','w2','b2'} (or {'w','b'} for the pp first
    layer); dense tail layers hold {'w','b'}; norm entries hold
    {'scale','bias'}. Weights are stored [in, out] (right-multiply).
    """
    layers: List[dict] = []
    norms: List[dict] = []
    use_pp = cfg.use_pp
    for i in range(cfg.n_layers):
        d_in, d_out = cfg.layer_sizes[i], cfg.layer_sizes[i + 1]
        rng, k1, k2, k3, k4 = jax.random.split(rng, 5)
        if i < cfg.n_graph_layers:
            if use_pp and i == 0:
                bound = 1.0 / (2 * d_in) ** 0.5
                layers.append({
                    "w": _uniform(k1, (2 * d_in, d_out), bound),
                    "b": _uniform(k2, (d_out,), bound),
                })
            elif cfg.model == "gcn":
                bound = 1.0 / d_in ** 0.5
                layers.append({
                    "w": _uniform(k1, (d_in, d_out), bound),
                    "b": _uniform(k2, (d_out,), bound),
                })
            elif cfg.model == "gat":
                # hidden layers concat H heads of d_out/H; a final graph
                # layer (producing logits) averages H heads of d_out
                h_ = cfg.n_heads
                dh = d_out if i == cfg.n_layers - 1 else d_out // h_
                bound = 1.0 / d_in ** 0.5
                layers.append({
                    "w": _uniform(k1, (d_in, h_ * dh), bound),
                    "b": _uniform(k2, (d_out,), bound),
                    "a_src": _uniform(k3, (h_, dh), 1.0 / dh ** 0.5),
                    "a_dst": _uniform(k4, (h_, dh), 1.0 / dh ** 0.5),
                })
            else:
                bound = 1.0 / d_in ** 0.5
                layers.append({
                    "w1": _uniform(k1, (d_in, d_out), bound),
                    "b1": _uniform(k2, (d_out,), bound),
                    "w2": _uniform(k3, (d_in, d_out), bound),
                    "b2": _uniform(k4, (d_out,), bound),
                })
        else:
            bound = 1.0 / d_in ** 0.5
            layers.append({
                "w": _uniform(k1, (d_in, d_out), bound),
                "b": _uniform(k2, (d_out,), bound),
            })
        if i < cfg.n_layers - 1 and cfg.norm is not None:
            norms.append({
                "scale": jnp.ones((d_out,), jnp.float32),
                "bias": jnp.zeros((d_out,), jnp.float32),
            })
    return {"layers": layers, "norms": norms}


def init_norm_state(cfg: ModelConfig) -> List[dict]:
    """Running mean/var for SyncBatchNorm (reference sync_bn.py:44-47);
    empty list unless norm == 'batch'."""
    if cfg.norm != "batch":
        return []
    return [
        {
            "mean": jnp.zeros((cfg.layer_sizes[i + 1],), jnp.float32),
            "var": jnp.ones((cfg.layer_sizes[i + 1],), jnp.float32),
        }
        for i in range(cfg.n_layers - 1)
    ]


def _layer_norm(h, scale, bias, eps=1e-5):
    # statistics in f32 even when activations flow in bf16
    hf = h.astype(jnp.float32)
    mu = hf.mean(axis=-1, keepdims=True)
    var = ((hf - mu) ** 2).mean(axis=-1, keepdims=True)
    out = (hf - mu) * jax.lax.rsqrt(var + eps) * scale + bias
    return out.astype(h.dtype)


def _sync_batch_norm_train(h, scale, bias, state, whole_size, psum,
                           row_mask=None, momentum=0.1, eps=1e-5):
    """Distributed BN over all rows across devices (reference
    sync_bn.py:13-22): statistics = psum of per-device sums divided by the
    global train size. `row_mask` excludes padded rows, whose values are
    nonzero layer outputs here (the reference has no padding; its rows are
    exactly the inner nodes).

    Intentional deviation: the reference all-reduces dweight/dbias inside
    the BN backward (sync_bn.py:35-36) AND again in the per-parameter
    reduce hook (reducer.py:30), making BN affine gradients P times the
    true distributed gradient. Here autodiff + the single grad psum yield
    the mathematically correct gradient (no double reduction).

    Returns (out, new_state)."""
    orig_dtype = h.dtype
    h = h.astype(jnp.float32)
    hm = h if row_mask is None else h * row_mask[:, None]
    sum_x = psum(hm.sum(axis=0))
    sum_x2 = psum((hm * hm).sum(axis=0))
    mean = sum_x / whole_size
    # Robustness deviation: the reference divides by the global TRAIN
    # size while summing over ALL local rows (sync_bn.py:19-20 with
    # model.py:38's train_size) — fine inductively (rows == train
    # nodes), but transductively rows > whole_size overscales `mean`,
    # and sum_x2 - mean*sum_x can then go NEGATIVE -> rsqrt(neg) -> NaN
    # (unexercised in the reference: no script selects --norm batch).
    # Clamping to >= 0 preserves exact parity whenever the reference
    # formula is well-posed and keeps training finite where it isn't.
    var = jnp.maximum((sum_x2 - mean * sum_x) / whole_size, 0.0)
    new_state = {
        "mean": state["mean"] * (1 - momentum) + mean * momentum,
        "var": state["var"] * (1 - momentum) + var * momentum,
    }
    x_hat = (h - mean) * jax.lax.rsqrt(var + eps)
    return (x_hat * scale + bias).astype(orig_dtype), new_state


def _sync_batch_norm_eval(h, scale, bias, state, eps=1e-5):
    hf = h.astype(jnp.float32)
    x_hat = (hf - state["mean"]) * jax.lax.rsqrt(state["var"] + eps)
    return (x_hat * scale + bias).astype(h.dtype)


def _gat_layer(fbuf, lp, edge_src, edge_dst, n_dst, n_heads, slope,
               is_last, out_dtype, chunk=None, gat_fn=None):
    """Multi-head edge-softmax attention aggregation.

    fbuf: [R, d_in] source rows (halo included). Returns [n_dst, d_out]
    — heads concatenated on hidden layers, averaged on a final (logits)
    layer. Attention statistics and all segment accumulations run in
    f32 regardless of the compute dtype; a final (logits) layer
    accumulates its matmul in f32 like dense() does.

    With `gat_fn` (the scatter-free attention-bucket kernel closure,
    ops/gat_bucket.make_device_gat_fn) the aggregation runs through
    precomputed bucket tables; otherwise over the raw edge list (pad
    edges carry dst == n_dst and fall into a discarded sentinel
    segment). `chunk` (cfg.spmm_chunk) bounds the raw path's per-pass
    edge intermediates the way spmm_mean's chunking does."""
    h_ = n_heads
    with jax.named_scope("dense"):
        z = jnp.matmul(fbuf, lp["w"].astype(fbuf.dtype),
                       preferred_element_type=jnp.float32 if is_last
                       else fbuf.dtype)
    dh = z.shape[-1] // h_
    z = z.reshape(-1, h_, dh)
    zf = z.astype(jnp.float32)
    el = (zf * lp["a_src"]).sum(-1)                    # [R, H]
    er = (zf[:n_dst] * lp["a_dst"]).sum(-1)            # [n_dst, H]

    if gat_fn is not None:
        with jax.named_scope("spmm"):
            out = gat_fn(z, el, er)                    # [n_dst, H, dh]
        out = out.mean(axis=1) if is_last \
            else out.reshape(n_dst, h_ * dh)
        return out.astype(out_dtype) + lp["b"].astype(out_dtype)

    er = jnp.concatenate([er, jnp.zeros((1, h_), jnp.float32)])
    n_seg = n_dst + 1
    e_cnt = edge_src.shape[0]

    # One code path: the unchunked case is a single chunk. Each pass
    # recomputes the cheap [E, H] logits; the expensive part (the
    # z[src] message gather) happens once, in the final pass.
    if not chunk or chunk >= e_cnt:
        chunk = max(e_cnt, 1)
    n_chunks = -(-e_cnt // chunk)
    pad = n_chunks * chunk - e_cnt
    # pad edges: dst -> sentinel segment, src -> row 0 (finite)
    es_p = jnp.pad(edge_src, (0, pad)).reshape(n_chunks, chunk)
    ed_p = jnp.pad(edge_dst, (0, pad),
                   constant_values=n_dst).reshape(n_chunks, chunk)

    def logits(es, ed):
        return jax.nn.leaky_relu(el[es] + er[ed], slope)  # [chunk, H]

    # carry inits must share the body outputs' device-varying type
    # under shard_map: a literal constant is 'unvarying' and scan
    # rejects the mismatch, so seed them with a varying zero
    vzero = el[:1].sum() * 0.0

    def max_body(m_acc, idx):
        m = jax.ops.segment_max(logits(*idx), idx[1], n_seg)
        return jnp.maximum(m_acc, m), None

    m, _ = jax.lax.scan(
        max_body, jnp.full((n_seg, h_), -jnp.inf, jnp.float32) + vzero,
        (es_p, ed_p))
    m = jnp.where(jnp.isfinite(m), m, 0.0)  # empty segments

    def sum_body(s_acc, idx):
        es, ed = idx
        ex = jnp.exp(logits(es, ed) - m[ed])
        return s_acc + jax.ops.segment_sum(ex, ed, n_seg), None

    s, _ = jax.lax.scan(
        sum_body, jnp.zeros((n_seg, h_), jnp.float32) + vzero,
        (es_p, ed_p))

    def out_body(o_acc, idx):
        es, ed = idx
        alpha = jnp.exp(logits(es, ed) - m[ed]) \
            / jnp.maximum(s[ed], 1e-16)
        msg = z[es].astype(jnp.float32) * alpha[..., None]
        return o_acc + jax.ops.segment_sum(msg, ed, n_seg), None

    out, _ = jax.lax.scan(
        out_body, jnp.zeros((n_seg, h_, dh), jnp.float32) + vzero,
        (es_p, ed_p))
    out = out[:n_dst]
    out = out.mean(axis=1) if is_last else out.reshape(n_dst, h_ * dh)
    return out.astype(out_dtype) + lp["b"].astype(out_dtype)


def _dropout(rng, h, rate, bits: int = 32):
    if rate <= 0.0:
        return h
    # named scope: the RNG + mask traffic show up as their own phase in
    # profiler traces / anatomy records (the floor terms --rng-impl rbg
    # and --dropout-bits 8 target)
    with jax.named_scope("dropout"):
        if bits == 8:
            # one random byte per element: keep iff byte >= thresh,
            # drop probability thresh/256 — the inverse scale uses the
            # QUANTIZED keep probability so the mask stays unbiased
            thresh = int(round(rate * 256.0))
            thresh = min(max(thresh, 1), 255)
            keep = jax.random.bits(rng, h.shape, jnp.uint8) >= jnp.uint8(
                thresh)
            keep_q = 1.0 - thresh / 256.0
            return jnp.where(keep, h / keep_q, 0.0)
        keep = jax.random.bernoulli(rng, 1.0 - rate, h.shape)
        return jnp.where(keep, h / (1.0 - rate), 0.0)


def forward(
    params: Params,
    cfg: ModelConfig,
    h: jax.Array,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    in_deg: jax.Array,
    n_dst: int,
    *,
    training: bool,
    rng: Optional[jax.Array] = None,
    comm_update: Optional[Callable[[int, jax.Array], jax.Array]] = None,
    norm_state: Optional[List[dict]] = None,
    psum: PsumFn = lambda x: x,
    eval_pp_agg: bool = False,
    row_mask: Optional[jax.Array] = None,
    spmm_fn: Optional[Callable[[jax.Array], jax.Array]] = None,
    gat_fn: Optional[Callable[..., jax.Array]] = None,
    halo_eval: bool = False,
    probe: Optional[Callable[[str, jax.Array], None]] = None,
) -> Tuple[jax.Array, List[dict]]:
    """Run the GraphSAGE stack; returns (logits [n_dst, n_class],
    updated norm_state).

    Training (`training=True`): `comm_update(i, h)` must return the
    aggregation source buffer (inner rows + halo rows) for graph layer i;
    it is skipped for layer 0 under use_pp (reference model.py:45-46).
    `in_deg` are the precomputed full-graph degrees.

    Eval (`training=False`): the graph is the full homogeneous graph
    (edge_src == edge_dst space, no halo), `in_deg` its own degrees, no
    dropout, running stats for BN. `eval_pp_agg=True` makes the first
    layer compute concat(feat, ah) @ W (use_pp eval path,
    module/layer.py:58-60).

    Sharded eval (`training=False, halo_eval=True`): the reference
    evaluates the full graph on one host (train.py:20-61); this mode
    instead evaluates through the partitioned layout — `comm_update`
    provides the synchronous halo exchange (no staleness), the feature
    input is the per-device shard (under use_pp: the precomputed concat,
    so layer 0 is a plain dense like in training) — with eval semantics
    everywhere else (no dropout, BN running stats). No single device
    ever materializes the full graph.

    `probe(phase, array)` (optional) is the numerics tripwire hook
    (resilience/numerics.py PHASES): called with each phase's output
    tensor so the caller can fold cheap in-graph finiteness counts into
    the step metrics. Phases emitted here: input / halo_concat / spmm /
    dense / norm / logits; loss and grads are the caller's to probe.
    """
    if probe is None:
        probe = lambda _name, _x: None  # noqa: E731 — trivial no-op
    norm_state = norm_state if norm_state is not None else []
    new_norm_state: List[dict] = []
    use_norm = cfg.norm is not None
    cdt = cfg.compute_dtype
    h = h.astype(cdt)
    probe("input", h)

    def dense(x, w, b, out_dtype):
        # params live in f32; cast to the compute dtype at use so the
        # matmul runs on the MXU in bf16 (the cast's transpose returns
        # f32 parameter cotangents automatically). out_dtype=f32 (the
        # logits layer) accumulates AND emits f32 from the bf16 matmul
        # via preferred_element_type, then adds the f32 bias — the
        # product is never rounded to bf16.
        with jax.named_scope("dense"):
            y = jnp.matmul(x, w.astype(x.dtype),
                           preferred_element_type=out_dtype)
            return y + b.astype(out_dtype)

    for i in range(cfg.n_layers):
      # named scope per layer: forward ops (and the backward ops XLA
      # derives from them) show up as "layer{i}/..." in profiler
      # traces instead of anonymous fusions (obs subsystem contract)
      with jax.named_scope(f"layer{i}"):
        is_graph = i < cfg.n_graph_layers
        # the network's last matmul produces logits in f32 for a stable
        # loss; hidden layers stay in the compute dtype
        out_dt = jnp.float32 if i == cfg.n_layers - 1 else cdt
        if training and cfg.dropout > 0:
            rng, sub = jax.random.split(rng)
        if is_graph:
            is_gcn = cfg.model == "gcn"
            is_gat = cfg.model == "gat"
            if is_gcn:
                # src-side symmetric normalization h_j / sqrt(d_j),
                # applied while every row is still on its owner (so the
                # halo exchange ships already-scaled values and halo
                # degrees are never needed); for full-graph eval the
                # rows ARE all the sources. d = full-graph in-degree of
                # A + I on both endpoints (the PyG gcn_norm convention).
                d_sqrt = jnp.sqrt(in_deg.astype(jnp.float32))
                h = (h.astype(jnp.float32)
                     / d_sqrt[: h.shape[0], None]).astype(cdt)
            if training or halo_eval:
                if (i > 0 or not cfg.use_pp) and comm_update is not None:
                    h = comm_update(i, h)
                    probe("halo_concat", h)
                if training and cfg.dropout > 0:
                    h = _dropout(sub, h, cfg.dropout, cfg.dropout_bits)
                lp = params["layers"][i]
                if cfg.use_pp and i == 0:
                    h = dense(h, lp["w"], lp["b"], out_dt)
                elif is_gat:
                    h = _gat_layer(h, lp, edge_src, edge_dst, n_dst,
                                   cfg.n_heads, cfg.leaky_slope,
                                   i == cfg.n_layers - 1, out_dt,
                                   chunk=cfg.spmm_chunk, gat_fn=gat_fn)
                else:
                    # spmm_fn (the bucket/block table kernels)
                    # returns the mean directly when injected
                    with jax.named_scope("spmm"):
                        if spmm_fn is not None:
                            ah = spmm_fn(h)
                        else:
                            ah = spmm_mean(h, edge_src, edge_dst,
                                           in_deg, n_dst,
                                           cfg.spmm_chunk,
                                           cfg.sorted_edges)
                    probe("spmm", ah)
                    if is_gcn:
                        # mean * sqrt(d_i) = (Σ_j h_j/sqrt(d_j))/sqrt(d_i)
                        ah = ah.astype(jnp.float32) * d_sqrt[:, None]
                        h = dense(ah.astype(cdt), lp["w"], lp["b"],
                                  out_dt)
                    else:
                        h = (dense(h[:n_dst], lp["w1"], lp["b1"], out_dt)
                             + dense(ah.astype(cdt), lp["w2"], lp["b2"],
                                     out_dt))
            elif is_gat:
                lp = params["layers"][i]
                h = _gat_layer(h, lp, edge_src, edge_dst, n_dst,
                               cfg.n_heads, cfg.leaky_slope,
                               i == cfg.n_layers - 1, out_dt,
                               chunk=cfg.spmm_chunk)
            else:
                lp = params["layers"][i]
                with jax.named_scope("spmm"):
                    ah = spmm_mean(h, edge_src, edge_dst, in_deg, n_dst,
                                   cfg.spmm_chunk, cfg.sorted_edges)
                if is_gcn:
                    ah = ah.astype(jnp.float32) * d_sqrt[:, None]
                    h = dense(ah.astype(cdt), lp["w"], lp["b"], out_dt)
                elif cfg.use_pp and i == 0:
                    if not eval_pp_agg:
                        raise ValueError(
                            "use_pp model evaluated without eval_pp_agg"
                        )
                    h = dense(jnp.concatenate([h, ah.astype(cdt)], axis=1),
                              lp["w"], lp["b"], out_dt)
                else:
                    h = (dense(h, lp["w1"], lp["b1"], out_dt)
                         + dense(ah.astype(cdt), lp["w2"], lp["b2"], out_dt))
        else:
            if training and cfg.dropout > 0:
                h = _dropout(sub, h, cfg.dropout, cfg.dropout_bits)
            lp = params["layers"][i]
            h = dense(h, lp["w"], lp["b"], out_dt)

        # one probe per layer output: the final layer's is the logits
        # phase, every other layer's the dense phase (aggregated over
        # layers by the collector — provenance wants the phase, the
        # per-layer split would only bloat the record)
        probe("logits" if i == cfg.n_layers - 1 else "dense", h)

        if i < cfg.n_layers - 1:
            if use_norm:
              with jax.named_scope("norm"):
                np_ = params["norms"][i]
                if cfg.norm == "layer":
                    h = _layer_norm(h, np_["scale"], np_["bias"])
                else:  # batch
                    if training:
                        h, ns = _sync_batch_norm_train(
                            h, np_["scale"], np_["bias"], norm_state[i],
                            cfg.train_size, psum, row_mask,
                        )
                        new_norm_state.append(ns)
                    else:
                        h = _sync_batch_norm_eval(
                            h, np_["scale"], np_["bias"], norm_state[i]
                        )
                probe("norm", h)
            h = jax.nn.relu(h)

    if training and cfg.norm == "batch":
        return h, new_norm_state
    return h, norm_state
