from .sage import ModelConfig, init_params, forward, init_norm_state

__all__ = ["ModelConfig", "init_params", "forward", "init_norm_state"]
