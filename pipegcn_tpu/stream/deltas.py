"""Versioned graph-delta batch format + epoch application schedule.

A delta batch is the unit of graph change: a set of directed COO edge
additions/deletions plus fully-described new nodes, stamped with a
monotonically increasing sequence id. Batches are applied atomically by
stream/patch.py (capacity is pre-checked against the reserved slack
before anything mutates).

On-disk formats (chosen by extension):

  *.jsonl   one JSON object per line; human-diffable. Every record
            carries a ``crc`` field — CRC32 of the canonical
            serialization (sorted keys, compact separators) of the
            record WITHOUT the crc field. A header line pins the format
            name and version.
  *.npz     array-native for large batches: per-batch arrays plus a
            per-batch CRC32 over the raw array bytes (dtype/shape
            prefixed, so a reinterpreting tamper is caught too).

Both loaders reject CRC mismatches, version skew, and non-monotonic
sequence ids loudly — a torn or tampered delta file must never be
half-applied to a serving topology.

Edge semantics: entries are DIRECTED COO edges, matching graph/csr.py
(message flows src -> dst). The synthetic generator emits both
directions of each undirected change, mirroring how the real datasets
store symmetric adjacency. Self-loops are managed by the patcher (every
node keeps exactly one; add-node implies its self-loop) and may not
appear in add/del lists.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

DELTA_FORMAT_VERSION = 1
_FORMAT_NAME = "pipegcn-deltas"


@dataclasses.dataclass
class DeltaBatch:
    """One atomic graph change set.

    add_edges / del_edges: [K, 2] int64 directed (src, dst) COO entries
    between nodes that exist BEFORE this batch's node additions are
    applied — except add_edges may also reference the batch's own new
    nodes (their ids are assigned first; see patch.py apply order).
    node_feat [M, F] float32, node_label [M] (int64, or [M, C] float32
    multi-hot), node_nbrs: M int64 arrays — each new node's undirected
    neighbor set (both directions are materialized, plus the node's
    self-loop). New nodes are never training nodes: local train-first
    renumbering would otherwise shift every existing local id.
    """

    seq: int
    add_edges: np.ndarray
    del_edges: np.ndarray
    node_feat: np.ndarray
    node_label: np.ndarray
    node_nbrs: Tuple[np.ndarray, ...] = ()

    @property
    def n_add(self) -> int:
        return int(self.add_edges.shape[0])

    @property
    def n_del(self) -> int:
        return int(self.del_edges.shape[0])

    @property
    def n_new(self) -> int:
        return int(self.node_feat.shape[0])

    @staticmethod
    def make(seq: int, add_edges=(), del_edges=(), node_feat=None,
             node_label=None, node_nbrs=()) -> "DeltaBatch":
        """Normalizing constructor: coerces lists/tuples into the
        canonical array dtypes (empty inputs become [0, 2] / [0, F=0]
        arrays so downstream shape logic never branches)."""
        ae = np.asarray(add_edges, np.int64).reshape(-1, 2)
        de = np.asarray(del_edges, np.int64).reshape(-1, 2)
        if node_feat is None:
            nf = np.zeros((0, 0), np.float32)
        else:
            nf = np.asarray(node_feat, np.float32)
            if nf.size == 0:
                nf = np.zeros((0, nf.shape[-1] if nf.ndim > 1 else 0),
                              np.float32)
            else:
                nf = nf.reshape(-1, nf.shape[-1] if nf.ndim > 1
                                else nf.size)
        if node_label is None:
            nl = np.zeros((nf.shape[0],), np.int64)
        else:
            nl = np.asarray(node_label)
            nl = nl.astype(np.float32) if nl.ndim == 2 else \
                nl.astype(np.int64).reshape(-1)
        nbrs = tuple(np.asarray(x, np.int64).reshape(-1)
                     for x in node_nbrs)
        if len(nbrs) != nf.shape[0]:
            raise ValueError(
                f"batch seq={seq}: {nf.shape[0]} new nodes but "
                f"{len(nbrs)} neighbor lists")
        return DeltaBatch(int(seq), ae, de, nf, nl, nbrs)


# ---------------------------------------------------------------------
# CRC guards
# ---------------------------------------------------------------------

def _canon_payload(b: DeltaBatch) -> dict:
    multilabel = b.node_label.ndim == 2
    return {
        "seq": int(b.seq),
        "add_edges": b.add_edges.tolist(),
        "del_edges": b.del_edges.tolist(),
        "node_feat": [[float(x) for x in row] for row in b.node_feat],
        "node_label": b.node_label.tolist(),
        "node_label_multilabel": bool(multilabel),
        "node_nbrs": [x.tolist() for x in b.node_nbrs],
    }


def _json_crc(payload: dict) -> int:
    blob = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()
    return zlib.crc32(blob) & 0xFFFFFFFF


def _array_crc(arrs: Sequence[np.ndarray]) -> int:
    # dtype/shape prefix per array: a tamper that reinterprets bytes
    # (e.g. swaps two same-size arrays) changes the stream too
    c = 0
    for a in arrs:
        a = np.ascontiguousarray(a)
        c = zlib.crc32(f"{a.dtype.str}|{a.shape}|".encode(), c)
        c = zlib.crc32(a.tobytes(), c)
    return c & 0xFFFFFFFF


def batch_crc(b: DeltaBatch) -> int:
    """Content CRC of a batch (the JSONL-record guard)."""
    return _json_crc(_canon_payload(b))


# ---------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------

def save_deltas(path: str, batches: Sequence[DeltaBatch]) -> None:
    """Write a delta file (format by extension: .npz or JSONL).

    Both formats write temp+rename through the storage-fault seams
    (resilience/storage.py): the loaders' CRC checks catch a torn file
    after the fact, but a serving topology polling `path` must never
    even SEE a half-written one (storage-fault audit: this writer used
    to write in place)."""
    _check_monotonic(batches, path)
    if path.endswith(".npz"):
        _save_npz(path, batches)
        return
    from ..resilience.storage import write_text_atomic

    hdr = {"format": _FORMAT_NAME, "version": DELTA_FORMAT_VERSION,
           "n_batches": len(batches)}
    hdr["crc"] = _json_crc(hdr)
    lines = [json.dumps(hdr, sort_keys=True)]
    for b in batches:
        payload = _canon_payload(b)
        payload["crc"] = _json_crc(payload)
        lines.append(json.dumps(payload, sort_keys=True))
    write_text_atomic(path, "\n".join(lines) + "\n", fsync=False)


def load_deltas(path: str) -> List[DeltaBatch]:
    """Load + verify a delta file. Raises ValueError on CRC mismatch,
    version skew, or non-monotonic sequence ids."""
    if path.endswith(".npz"):
        return _load_npz(path)
    batches: List[DeltaBatch] = []
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty delta file")
    hdr = json.loads(lines[0])
    _check_header(hdr, path)
    for i, ln in enumerate(lines[1:]):
        rec = json.loads(ln)
        crc = rec.pop("crc", None)
        if crc is None or _json_crc(rec) != crc:
            raise ValueError(
                f"{path}: CRC mismatch on batch record {i} "
                f"(seq={rec.get('seq')}) — torn write or tamper")
        multilabel = rec.get("node_label_multilabel", False)
        nl = np.asarray(rec["node_label"],
                        np.float32 if multilabel else np.int64)
        nf = np.asarray(rec["node_feat"], np.float32)
        if nf.size == 0:
            nf = nf.reshape(0, 0)
        batches.append(DeltaBatch.make(
            rec["seq"], rec["add_edges"], rec["del_edges"],
            nf, nl, [np.asarray(x, np.int64) for x in rec["node_nbrs"]],
        ))
    _check_monotonic(batches, path)
    return batches


def _check_header(hdr: dict, path: str) -> None:
    crc = dict(hdr)
    got = crc.pop("crc", None)
    if got is None or _json_crc(crc) != got:
        raise ValueError(f"{path}: header CRC mismatch")
    if hdr.get("format") != _FORMAT_NAME:
        raise ValueError(
            f"{path}: not a {_FORMAT_NAME} file "
            f"(format={hdr.get('format')!r})")
    if hdr.get("version") != DELTA_FORMAT_VERSION:
        raise ValueError(
            f"{path}: delta format version {hdr.get('version')} != "
            f"supported {DELTA_FORMAT_VERSION}")


def _check_monotonic(batches: Sequence[DeltaBatch], path: str) -> None:
    seqs = [b.seq for b in batches]
    if any(b >= a for a, b in zip(seqs[1:], seqs[:-1])):
        raise ValueError(
            f"{path}: sequence ids must be strictly increasing, "
            f"got {seqs}")


def _save_npz(path: str, batches: Sequence[DeltaBatch]) -> None:
    arrs = {"version": np.int64(DELTA_FORMAT_VERSION),
            "n_batches": np.int64(len(batches))}
    for i, b in enumerate(batches):
        k = f"b{i:05d}_"
        nbr_ptr = np.zeros(len(b.node_nbrs) + 1, np.int64)
        np.cumsum([x.size for x in b.node_nbrs], out=nbr_ptr[1:])
        nbr_flat = (np.concatenate(b.node_nbrs)
                    if b.node_nbrs else np.zeros(0, np.int64))
        parts = [np.int64(b.seq), b.add_edges, b.del_edges,
                 b.node_feat, b.node_label, nbr_flat, nbr_ptr]
        arrs[k + "seq"] = parts[0]
        arrs[k + "add_edges"] = parts[1]
        arrs[k + "del_edges"] = parts[2]
        arrs[k + "node_feat"] = parts[3]
        arrs[k + "node_label"] = parts[4]
        arrs[k + "nbr_flat"] = parts[5]
        arrs[k + "nbr_ptr"] = parts[6]
        arrs[k + "crc"] = np.int64(_array_crc(parts))
    from ..resilience.storage import FAULTY_IO

    # np.savez appends ".npz" unless the name already ends with it
    FAULTY_IO.gate(path, "open")
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    try:
        np.savez(tmp, **arrs)
        FAULTY_IO.gate(path, "write")
        FAULTY_IO.maybe_tear(tmp)
        FAULTY_IO.gate(path, "rename")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _load_npz(path: str) -> List[DeltaBatch]:
    with np.load(path) as z:
        if int(z["version"]) != DELTA_FORMAT_VERSION:
            raise ValueError(
                f"{path}: delta format version {int(z['version'])} != "
                f"supported {DELTA_FORMAT_VERSION}")
        batches = []
        for i in range(int(z["n_batches"])):
            k = f"b{i:05d}_"
            parts = [z[k + "seq"], z[k + "add_edges"],
                     z[k + "del_edges"], z[k + "node_feat"],
                     z[k + "node_label"], z[k + "nbr_flat"],
                     z[k + "nbr_ptr"]]
            if _array_crc(parts) != int(z[k + "crc"]):
                raise ValueError(
                    f"{path}: CRC mismatch on batch {i} — torn write "
                    f"or tamper")
            seq, ae, de, nf, nl, flat, ptr = parts
            nbrs = [flat[ptr[j]:ptr[j + 1]] for j in range(ptr.size - 1)]
            batches.append(DeltaBatch.make(int(seq), ae, de, nf, nl,
                                           nbrs))
    _check_monotonic(batches, path)
    return batches


# ---------------------------------------------------------------------
# epoch application schedule (--stream-plan)
# ---------------------------------------------------------------------

_PLAN_RE = re.compile(r"^(.+)@(\d+)(?::(\d+))?$")


class StreamPlan:
    """Epoch-keyed delta schedule, parsed from comma-separated
    ``FILE@E0[:everyN]`` entries: batch j of FILE is applied at the
    boundary of epoch E0 + j*N (N defaults to 1). Like FaultPlan, every
    scheduled batch fires at most once and ``due()`` uses an at-or-
    before comparison so fused-epoch blocks cannot silently skip one;
    ``skip_before`` retires batches a resumed run already lived
    through."""

    def __init__(self, scheduled: List[Tuple[int, DeltaBatch]]):
        self._entries = sorted(scheduled, key=lambda e: (e[0], e[1].seq))
        self._done = [False] * len(self._entries)

    @classmethod
    def parse(cls, spec: str) -> "StreamPlan":
        scheduled: List[Tuple[int, DeltaBatch]] = []
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            m = _PLAN_RE.match(raw)
            if not m:
                raise ValueError(
                    f"bad stream-plan entry {raw!r}: expected "
                    f"FILE@epoch[:everyN] (e.g. deltas.jsonl@10:5)")
            path, e0 = m.group(1), int(m.group(2))
            every = int(m.group(3)) if m.group(3) else 1
            if every < 1:
                raise ValueError(
                    f"stream-plan entry {raw!r}: everyN must be >= 1")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"stream-plan file not found: {path}")
            for j, b in enumerate(load_deltas(path)):
                scheduled.append((e0 + j * every, b))
        return cls(scheduled)

    def __len__(self) -> int:
        return len(self._entries)

    def __bool__(self) -> bool:
        return bool(self._entries)

    def remaining(self) -> int:
        return sum(1 for d in self._done if not d)

    def skip_before(self, start_epoch: int) -> None:
        """Retire batches scheduled strictly before `start_epoch`.

        LEGACY resume semantics, only correct when no delta journal is
        in play: it assumes the resumed graph already contains the
        pre-resume deltas, which is false (resume rebuilds the nominal
        graph) — journaled runs use :meth:`skip_journaled` after WAL
        replay instead (stream/journal.py)."""
        for i, (e, _) in enumerate(self._entries):
            if e < start_epoch:
                self._done[i] = True

    def skip_journaled(self, last_seq: int) -> int:
        """Journal-aware resume: retire exactly the batches with
        seq <= `last_seq` (the checkpoint watermark — WAL replay just
        re-applied them). Later-scheduled batches stay live even when
        their epoch predates the resume point, so nothing is dropped on
        the floor. Returns the number retired."""
        n = 0
        for i, (_, b) in enumerate(self._entries):
            if not self._done[i] and b.seq <= last_seq:
                self._done[i] = True
                n += 1
        return n

    def batches_upto(self, last_seq: int) -> List[DeltaBatch]:
        """All scheduled batches with seq <= `last_seq`, regardless of
        done state — the re-derivation source when the journal lost its
        tail (stream/journal.py replay_for_resume)."""
        return [b for (_, b) in self._entries if b.seq <= last_seq]

    def due(self, epoch: int) -> List[DeltaBatch]:
        """Consume and return every batch scheduled at-or-before
        `epoch`, in schedule order."""
        out = []
        for i, (e, b) in enumerate(self._entries):
            if not self._done[i] and e <= epoch:
                self._done[i] = True
                out.append(b)
        return out

    def next_epoch(self, after: int) -> Optional[int]:
        """Smallest unconsumed scheduled epoch >= `after` (for fused-
        block clamping: the trainer must visit that boundary)."""
        nxt = [e for i, (e, _) in enumerate(self._entries)
               if not self._done[i] and e >= after]
        return min(nxt) if nxt else None
