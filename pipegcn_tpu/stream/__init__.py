"""Streaming-graph subsystem: delta ingestion + incremental patching.

The paper trains on a static full graph; production graphs change while
you train and serve. This package closes that gap (ROADMAP item 4,
second half) without ever re-running METIS:

  deltas.py   versioned add-edge/del-edge/add-node batch format
              (CRC-guarded JSONL or npz, monotonic sequence ids) and
              the ``FILE@epoch[:everyN]`` application schedule
  patch.py    incremental CSR + sharded-table patching: new edges land
              in the existing partition of their endpoints, send/recv
              lists and halo slots grow in place through the reserved
              ``--stream-slack`` headroom, so the compiled step's
              shapes are STATIC across deltas. Bit-identity of the
              patched ShardedGraph vs a from-scratch build of the same
              final edge list is the correctness oracle.
  journal.py  write-ahead delta journal: every applied batch is made
              durable BEFORE it mutates the topology, checkpoints
              stamp a seq/topo_generation watermark, and every resume
              path (trainer --resume, elastic replan, serving replica
              restart) replays the journal so a kill between apply and
              checkpoint can never silently revert the graph.

See docs/STREAMING.md for the delta format, the slack model, and the
drift-measurement methodology.
"""

from .deltas import (DELTA_FORMAT_VERSION, DeltaBatch, StreamPlan,
                     load_deltas, save_deltas)
from .journal import (JOURNAL_FORMAT_VERSION, DeltaJournal,
                      JournalCorrupt, replay_for_resume,
                      verify_against_rebuild)
from .patch import GraphPatcher, PatchReport, SlackExhausted

__all__ = [
    "DELTA_FORMAT_VERSION",
    "DeltaBatch",
    "StreamPlan",
    "load_deltas",
    "save_deltas",
    "JOURNAL_FORMAT_VERSION",
    "DeltaJournal",
    "JournalCorrupt",
    "replay_for_resume",
    "verify_against_rebuild",
    "GraphPatcher",
    "PatchReport",
    "SlackExhausted",
]
