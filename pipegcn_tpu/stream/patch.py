"""Incremental CSR + sharded-table patching for streaming graph deltas.

GraphPatcher applies DeltaBatches to a host Graph AND its ShardedGraph
in place, with **no re-partition**: new edges land on the existing
partition of their destination endpoint, new nodes on the owner of
their highest-in-degree neighbor, and send/recv lists + halo slots grow
through the slack headroom reserved at build time (``--stream-slack``)
so every compiled shape stays static across deltas.

Correctness oracle: after any batch sequence, every array of the
patched ShardedGraph is bit-identical to a from-scratch
``ShardedGraph.build`` of the patcher's host graph with the same padded
dimensions (``min_n_max``/``min_b_max``/``min_e_max`` floors). The
patcher guarantees this by construction:

  * local ids never shift — new nodes get global ids above every
    existing id and are never training nodes, so the build()'s
    (part, ~train, global id) lexsort appends them exactly where the
    patcher does (the end of their partition's block). Layouts with
    extra sort keys (reorder/cluster) are refused at init.
  * host COO order is maintained deterministically (deletions keep
    relative order; additions append: per new node its self-loop then
    both directions of each neighbor edge, then the batch's add_edges)
    and affected devices' edge arrays are recomputed from the host COO
    with build()'s exact localization + stable CSR sort, so the
    tie-break order matches a rebuild of the same Graph object.
  * send lists stay sorted by local id under in-place insertion and
    removal, matching _send_structures' (owner, dest, local id) sort.

Capacity is pre-checked against the padded dims BEFORE any mutation;
exhaustion raises :class:`SlackExhausted` naming the required floors,
or (``allow_repad=True``) triggers the loud re-pad: a from-scratch
rebuild at grown padding, after which the batch is re-applied. A re-pad
changes compiled shapes — consumers must rebuild device state (the
trainer and serving engine both do, loudly).
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..graph.csr import Graph
from ..partition.halo import ShardedGraph
from .deltas import DeltaBatch

# ndata keys the patcher knows how to extend for new nodes; anything
# else on the host graph would silently desynchronize from a rebuild
_NDATA_KEYS = ("feat", "label", "train_mask", "val_mask", "test_mask",
               "in_deg")


class SlackExhausted(RuntimeError):
    """A delta batch does not fit the reserved padding. ``required``
    holds the raw (unpadded) per-dimension floors a re-pad needs."""

    def __init__(self, msg: str, required: Dict[str, int]):
        super().__init__(msg)
        self.required = dict(required)


@dataclasses.dataclass
class PatchReport:
    """What one batch application did — the payload of the contracted
    ``stream`` observability record plus the invalidation masks the
    trainer carry-flush and serving freshness paths consume."""

    seq: int
    edges_added: int
    edges_deleted: int
    nodes_added: int
    patch_ms: float
    slack_remaining: Dict[str, int]
    repadded: bool = False
    tables_rebuilt: int = 0   # filled in by the trainer/serving layer
    # [P, P-1, b_max] bool: send-list entries whose content or position
    # changed (None after a re-pad: everything changed)
    changed_send: Optional[np.ndarray] = None
    # [P, n_max] bool: inner rows whose in_degree changed (incl. new)
    deg_changed: Optional[np.ndarray] = None
    # [P, n_max] bool: rows added by this batch
    new_rows: Optional[np.ndarray] = None
    touched_parts: Tuple[int, ...] = ()


def flush_masks(changed_send: np.ndarray, num_parts: int, b_max: int):
    """(receiver [P, H], sender [P, H]) bool masks over halo-flat rows
    from a changed-send-entry cube. The pipelined carry is two-view:
    ``halo``/``favg`` are consumed where RECEIVED (device q = (p+d)%P
    holds owner p's distance-d block), ``bgrad``/``bavg`` where SENT
    (make_stale_concat's bwd scatters through the device's own send
    list) — a flush must zero each in its own frame."""
    P = num_parts
    H = (P - 1) * b_max
    recv = np.zeros((P, H), bool)
    send = np.zeros((P, H), bool)
    for p in range(P):
        for d in range(1, P):
            ch = changed_send[p, d - 1]
            if not ch.any():
                continue
            flat = slice((d - 1) * b_max, d * b_max)
            send[p, flat] |= ch
            recv[(p + d) % P, flat] |= ch
    return recv, send


class GraphPatcher:
    """In-place delta application against a (host Graph, ShardedGraph,
    partition assignment) triple. Mutates all three; the host graph and
    ``parts`` stay rebuild-consistent so the bit-identity oracle (and
    the loud re-pad) can always fall back to ``ShardedGraph.build``."""

    def __init__(self, g: Graph, sg: ShardedGraph, parts: np.ndarray,
                 pad_to: int = 8, slack: float = 0.10,
                 verify_checksum: bool = True):
        if sg.reorder != "none":
            raise ValueError(
                "streaming requires the base layout: reorder="
                f"{sg.reorder!r} renumbers local ids by a locality key "
                "the patcher cannot extend incrementally")
        if sg.local_parts is not None:
            raise ValueError(
                "streaming patches the full [P, ...] array stack; "
                "elastic local_parts views are not patchable")
        for arr in (sg.edge_src, sg.edge_dst):
            if not isinstance(arr, np.ndarray):
                raise ValueError(
                    "streaming needs writable padded edge arrays; "
                    "trim_edges artifacts store per-rank views only")
        self.g = g
        self.sg = sg
        self.parts = np.asarray(parts, np.int32).copy()
        self.pad_to = int(pad_to)
        self.slack = float(slack)
        self.P = sg.num_parts
        if self.parts.shape[0] != g.num_nodes:
            raise ValueError("parts length != num_nodes")
        if verify_checksum and sg.source_edge_checksum not in (
                -1, ShardedGraph.edge_checksum(g)):
            raise ValueError(
                "host graph does not match the sharded graph "
                "(edge checksum mismatch) — patching would diverge")
        self.local_id = self._derive_local_ids()
        self._verify_layout()
        self.pair_count = self._build_pair_counts()
        self.last_seq = -1

    # ---------------- init-time derivations ---------------------------

    def _derive_local_ids(self) -> np.ndarray:
        sg = self.sg
        local = np.full(self.g.num_nodes, -1, np.int64)
        for p in range(self.P):
            n = int(sg.inner_count[p])
            gn = sg.global_nid[p, :n]
            if np.any(self.parts[gn] != p):
                raise ValueError(
                    f"partition assignment disagrees with shard {p}'s "
                    "global_nid rows")
            local[gn] = np.arange(n)
        if np.any(local < 0):
            raise ValueError("sharded graph does not cover every node")
        return local

    def _verify_layout(self) -> None:
        # the append-at-end invariant needs (part, ~train, global id)
        # ordering exactly: ascending global ids within each part's
        # train and non-train blocks (a cluster-keyed layout fails here)
        sg = self.sg
        for p in range(self.P):
            n, t = int(sg.inner_count[p]), int(sg.train_count[p])
            gn = sg.global_nid[p, :n]
            for blk, name in ((gn[:t], "train"), (gn[t:], "non-train")):
                if blk.size > 1 and np.any(np.diff(blk) <= 0):
                    raise ValueError(
                        f"shard {p}'s {name} block is not in global-id "
                        "order — streaming requires the base (no "
                        "cluster/reorder key) layout")

    def _build_pair_counts(self) -> Dict[int, int]:
        g, P = self.g, self.P
        cross = self.parts[g.src] != self.parts[g.dst]
        fused = (g.src[cross].astype(np.int64) * P
                 + self.parts[g.dst[cross]])
        keys, counts = np.unique(fused, return_counts=True)
        return dict(zip(keys.tolist(), counts.tolist()))

    # ---------------- public queries ----------------------------------

    def slack_remaining(self) -> Dict[str, int]:
        sg = self.sg
        b_used = int(sg.send_counts.max()) if sg.send_counts.size else 0
        return {
            "n": int(sg.n_max - sg.inner_count.max()),
            "b": int(sg.b_max - b_used),
            "e": int(sg.e_max - sg.edge_count.max()),
        }

    # ---------------- batch application -------------------------------

    def apply(self, batch: DeltaBatch,
              allow_repad: bool = False) -> PatchReport:
        t0 = time.perf_counter()
        self._validate_batch(batch)

        plan = self._plan(batch)
        try:
            self._capacity_check(plan)
        except SlackExhausted as exc:
            if not allow_repad:
                raise
            self._repad(exc.required)
            rep = self.apply(batch, allow_repad=False)
            rep.repadded = True
            rep.patch_ms = (time.perf_counter() - t0) * 1e3
            return rep

        report = self._commit(batch, plan)
        self.last_seq = batch.seq
        report.patch_ms = (time.perf_counter() - t0) * 1e3
        return report

    # ---------------- validation --------------------------------------

    def _validate_batch(self, batch: DeltaBatch) -> None:
        g, sg = self.g, self.sg
        if batch.seq <= self.last_seq:
            raise ValueError(
                f"batch seq {batch.seq} <= last applied {self.last_seq}"
                " — delta sequence ids must be strictly increasing")
        N, M = g.num_nodes, batch.n_new
        for name, arr in (("del", batch.del_edges),
                          ("add", batch.add_edges)):
            if arr.size and np.any(arr[:, 0] == arr[:, 1]):
                raise ValueError(
                    f"{name}-edge list contains self-loops; self-loops "
                    "are managed by the patcher (one per node, always)")
        if batch.del_edges.size and (
                batch.del_edges.min() < 0 or batch.del_edges.max() >= N):
            raise ValueError("del-edge endpoint out of range")
        if batch.add_edges.size and (
                batch.add_edges.min() < 0
                or batch.add_edges.max() >= N + M):
            raise ValueError("add-edge endpoint out of range")
        if M:
            if batch.node_feat.shape[1] != sg.n_feat:
                raise ValueError(
                    f"new-node feature width {batch.node_feat.shape[1]}"
                    f" != graph n_feat {sg.n_feat}")
            if sg.multilabel != (batch.node_label.ndim == 2):
                raise ValueError(
                    "new-node label arity does not match the graph "
                    f"(multilabel={sg.multilabel})")
            if not sg.multilabel and batch.node_label.size and (
                    batch.node_label.min() < 0
                    or batch.node_label.max() >= sg.n_class):
                raise ValueError(
                    f"new-node label outside [0, {sg.n_class}) would "
                    "change the rebuilt class count")
            for i, nb in enumerate(batch.node_nbrs):
                if nb.size == 0:
                    raise ValueError(
                        f"new node {i} has no neighbors — owner "
                        "assignment needs at least one")
                if nb.min() < 0 or nb.max() >= N:
                    raise ValueError(
                        f"new node {i} references a neighbor outside "
                        "the pre-batch graph")

    # ---------------- planning (no mutation) --------------------------

    def _plan(self, batch: DeltaBatch) -> Dict[str, np.ndarray]:
        """Resolve everything the batch will do — new-node owners, full
        directed add/del lists, pair-count transitions — against the
        CURRENT graph, without mutating it."""
        g, P = self.g, self.P
        N, M = g.num_nodes, batch.n_new
        in_deg = np.asarray(g.ndata["in_deg"])

        # owner of each new node: partition of its highest-in-degree
        # neighbor (first on ties), measured on the pre-batch graph
        new_parts = np.empty(M, np.int32)
        for i, nb in enumerate(batch.node_nbrs):
            new_parts[i] = self.parts[nb[int(np.argmax(in_deg[nb]))]]

        # canonical host-COO append order: per new node its self-loop
        # then (u, v), (v, u) per neighbor; then the batch's add_edges
        adds = []
        for i, nb in enumerate(batch.node_nbrs):
            u = N + i
            adds.append([[u, u]])
            pair = np.empty((nb.size * 2, 2), np.int64)
            pair[0::2, 0], pair[0::2, 1] = u, nb
            pair[1::2, 0], pair[1::2, 1] = nb, u
            adds.append(pair)
        if batch.add_edges.size:
            adds.append(batch.add_edges)
        add = (np.concatenate([np.asarray(a, np.int64) for a in adds])
               if adds else np.zeros((0, 2), np.int64))
        dele = batch.del_edges

        # simple-graph discipline: dels must exist (exactly once, by
        # construction), adds must not duplicate a surviving edge or
        # each other
        NN = N + M
        cur = g.src.astype(np.int64) * NN + g.dst
        cur_sorted = np.sort(cur)
        if dele.size:
            dk = dele[:, 0] * NN + dele[:, 1]
            if np.unique(dk).size != dk.size:
                raise ValueError("duplicate del-edge entries in batch")
            pos = np.searchsorted(cur_sorted, dk)
            pos = np.clip(pos, 0, max(cur_sorted.size - 1, 0))
            if cur_sorted.size == 0 or np.any(cur_sorted[pos] != dk):
                raise ValueError(
                    "del-edge not present in the current graph")
        else:
            dk = np.zeros(0, np.int64)
        if add.size:
            ak = add[:, 0] * NN + add[:, 1]
            if np.unique(ak).size != ak.size:
                raise ValueError("duplicate add-edge entries in batch")
            pos = np.searchsorted(cur_sorted, ak)
            pos = np.clip(pos, 0, max(cur_sorted.size - 1, 0))
            present = (cur_sorted.size > 0) & (cur_sorted[
                np.minimum(pos, cur_sorted.size - 1)] == ak)
            # present is fine only if the same key is also deleted
            clash = present & ~np.isin(ak, dk)
            if np.any(clash):
                raise ValueError(
                    "add-edge duplicates an existing edge (graph must "
                    "stay simple)")

        # pair-count transitions for cross edges
        parts_ext = np.concatenate([self.parts, new_parts])
        delta: Dict[int, int] = {}
        for arr, sign in ((dele, -1), (add, +1)):
            if not arr.size:
                continue
            pu, pv = parts_ext[arr[:, 0]], parts_ext[arr[:, 1]]
            cross = pu != pv
            fused = arr[cross, 0] * P + pv[cross]
            for k, c in zip(*np.unique(fused, return_counts=True)):
                delta[int(k)] = delta.get(int(k), 0) + sign * int(c)
        return {"add": add, "del": dele, "new_parts": new_parts,
                "pair_delta": delta, "parts_ext": parts_ext}

    def _capacity_check(self, plan: Dict[str, np.ndarray]) -> None:
        sg, P = self.sg, self.P
        new_sizes = sg.inner_count + np.bincount(
            plan["new_parts"], minlength=P).astype(np.int32)
        ecnt = sg.edge_count.astype(np.int64)
        parts_ext = plan["parts_ext"]
        if plan["del"].size:
            ecnt -= np.bincount(parts_ext[plan["del"][:, 1]],
                                minlength=P)
        if plan["add"].size:
            ecnt += np.bincount(parts_ext[plan["add"][:, 1]],
                                minlength=P)
        # per-(owner, dist) send-count deltas from pair transitions
        sc = sg.send_counts.copy() if sg.send_counts.size else \
            np.zeros((P, max(P - 1, 1)), np.int32)
        for k, dv in plan["pair_delta"].items():
            u, q = k // P, k % P
            cur = self.pair_count.get(k, 0)
            new = cur + dv
            if new < 0:
                raise ValueError(
                    "pair-count underflow: delta deletes more "
                    f"(u={u} -> part {q}) edges than exist")
            p = int(self.parts[u]) if u < self.parts.shape[0] else \
                int(plan["new_parts"][u - self.parts.shape[0]])
            d = (q - p) % P
            if cur == 0 and new > 0:
                sc[p, d - 1] += 1
            elif cur > 0 and new == 0:
                sc[p, d - 1] -= 1
        req = {
            "min_n_max": int(new_sizes.max()),
            "min_b_max": int(sc.max()) if P > 1 else 0,
            "min_e_max": int(ecnt.max()),
        }
        over = []
        if req["min_n_max"] > sg.n_max:
            over.append(f"nodes {req['min_n_max']} > n_max {sg.n_max}")
        if req["min_b_max"] > sg.b_max and P > 1:
            over.append(f"send {req['min_b_max']} > b_max {sg.b_max}")
        if req["min_e_max"] > sg.e_max:
            over.append(f"edges {req['min_e_max']} > e_max {sg.e_max}")
        if over:
            raise SlackExhausted(
                "stream slack exhausted (" + "; ".join(over) + ") — "
                "re-pad required (--stream-slack reserves headroom; "
                "apply(allow_repad=True) rebuilds loudly)", req)

    # ---------------- loud re-pad -------------------------------------

    def _repad(self, required: Dict[str, int]) -> None:
        """From-scratch rebuild of the sharded arrays at grown padding
        (same graph, same partition assignment, same local ids — only
        the padded dims change). Compiled shapes change: every consumer
        must rebuild device state."""
        sg = self.sg
        grow = 1.0 + max(self.slack, 0.0)
        mins = {k: int(np.ceil(v * grow)) for k, v in required.items()}
        print(
            f"[stream] slack exhausted: re-padding sharded graph "
            f"(n_max {sg.n_max}, b_max {sg.b_max}, e_max {sg.e_max}) "
            f"-> floors {mins} — compiled shapes change, device state "
            f"must be rebuilt", file=sys.stderr, flush=True)
        new_sg = ShardedGraph.build(
            self.g, self.parts, n_parts=self.P, pad_to=self.pad_to,
            slack=self.slack, min_n_max=mins["min_n_max"],
            min_b_max=mins["min_b_max"], min_e_max=mins["min_e_max"])
        new_sg.cache_dir = sg.cache_dir
        self._replace_sg(new_sg)

    def _replace_sg(self, new_sg: ShardedGraph) -> None:
        # rebind in place so holders of the patcher see the new arrays;
        # holders of the OLD sg object must re-read it via the patcher
        self.sg = new_sg
        self.local_id = self._derive_local_ids()
        self.pair_count = self._build_pair_counts()

    # ---------------- commit ------------------------------------------

    def _commit(self, batch: DeltaBatch,
                plan: Dict[str, np.ndarray]) -> PatchReport:
        g, sg, P = self.g, self.sg, self.P
        N, M = g.num_nodes, batch.n_new
        n_max, b_max = sg.n_max, sg.b_max
        add, dele = plan["add"], plan["del"]
        new_parts = plan["new_parts"]
        NN = N + M

        # ---- host graph: nodes ---------------------------------------
        unknown = [k for k in g.ndata if k not in _NDATA_KEYS]
        if unknown:
            raise ValueError(
                f"host graph carries ndata keys {unknown} the patcher "
                "cannot extend for new nodes")
        if M:
            g.ndata["feat"] = np.concatenate(
                [g.ndata["feat"], batch.node_feat.astype(np.float32)])
            lab = g.ndata["label"]
            if sg.multilabel:
                g.ndata["label"] = np.concatenate(
                    [lab, batch.node_label.astype(lab.dtype)])
            else:
                g.ndata["label"] = np.concatenate(
                    [lab, batch.node_label.astype(lab.dtype)])
            for k in ("train_mask", "val_mask", "test_mask"):
                g.ndata[k] = np.concatenate(
                    [g.ndata[k], np.zeros(M, bool)])
            g.ndata["in_deg"] = np.concatenate(
                [g.ndata["in_deg"], np.zeros(M, np.float32)])
            g.num_nodes = NN

        # ---- host graph: edges (canonical order) ---------------------
        if dele.size:
            cur = g.src.astype(np.int64) * NN + g.dst
            keep = ~np.isin(cur, dele[:, 0] * NN + dele[:, 1])
            g.src, g.dst = g.src[keep], g.dst[keep]
        if add.size:
            g.src = np.concatenate(
                [g.src, add[:, 0].astype(g.src.dtype)])
            g.dst = np.concatenate(
                [g.dst, add[:, 1].astype(g.dst.dtype)])
        ind = g.ndata["in_deg"]
        if dele.size:
            np.subtract.at(ind, dele[:, 1], 1.0)
        if add.size:
            np.add.at(ind, add[:, 1], 1.0)

        # ---- local ids / parts for new nodes -------------------------
        deg_changed = np.zeros((P, n_max), bool)
        new_rows = np.zeros((P, n_max), bool)
        if M:
            self.parts = np.concatenate([self.parts, new_parts])
            new_local = np.empty(M, np.int64)
            cnt = sg.inner_count.astype(np.int64).copy()
            for i in range(M):
                p = int(new_parts[i])
                new_local[i] = cnt[p]
                cnt[p] += 1
            self.local_id = np.concatenate([self.local_id, new_local])
            gids = np.arange(N, NN, dtype=np.int64)
            sg.feat[new_parts, new_local] = batch.node_feat
            sg.label[new_parts, new_local] = (
                batch.node_label.astype(sg.label.dtype))
            sg.global_nid[new_parts, new_local] = gids
            sg.inner_count = cnt.astype(np.int32)
            new_rows[new_parts, new_local] = True

        # in_deg rows that changed (destinations of any add/del + new)
        touched_dst = np.concatenate([
            a for a in (dele[:, 1] if dele.size else None,
                        add[:, 1] if add.size else None)
            if a is not None]) if (dele.size or add.size) else \
            np.zeros(0, np.int64)
        if touched_dst.size:
            tv = np.unique(touched_dst)
            sg.in_deg[self.parts[tv], self.local_id[tv]] = ind[tv]
            deg_changed[self.parts[tv], self.local_id[tv]] = True
        deg_changed |= new_rows

        # ---- send lists: pair transitions ----------------------------
        changed = np.zeros((P, max(P - 1, 1), max(b_max, 1)), bool)
        touched_pd = set()
        for k in sorted(plan["pair_delta"]):
            dv = plan["pair_delta"][k]
            u, q = k // P, k % P
            cur = self.pair_count.get(k, 0)
            new = cur + dv
            p = int(self.parts[u])
            d = (q - p) % P
            if cur == 0 and new > 0:
                self._send_insert(p, d, int(self.local_id[u]), changed)
                touched_pd.add((p, d))
            elif cur > 0 and new == 0:
                self._send_remove(p, d, int(self.local_id[u]), changed)
                touched_pd.add((p, d))
            if new:
                self.pair_count[k] = new
            else:
                self.pair_count.pop(k, None)

        # ---- per-device edge arrays ----------------------------------
        affected = set(new_parts.tolist())
        if dele.size:
            affected |= set(self.parts[dele[:, 1]].tolist())
        if add.size:
            affected |= set(self.parts[add[:, 1]].tolist())
        affected |= {(p + d) % P for p, d in touched_pd}
        for q in sorted(affected):
            self._rebuild_device_edges(int(q))

        # the checksum keys the derived-table disk cache; num_nodes
        # enters the hash, so recompute from the host graph
        sg.source_edge_checksum = ShardedGraph.edge_checksum(g)

        changed_view = changed[:, :P - 1, :b_max] if P > 1 else \
            np.zeros((P, 0, 0), bool)
        return PatchReport(
            seq=batch.seq,
            edges_added=int(add.shape[0]),
            edges_deleted=int(dele.shape[0]),
            nodes_added=M,
            patch_ms=0.0,
            slack_remaining=self.slack_remaining(),
            changed_send=changed_view,
            deg_changed=deg_changed,
            new_rows=new_rows,
            touched_parts=tuple(sorted(affected)),
        )

    # ---------------- send-list surgery -------------------------------

    def _send_insert(self, p: int, d: int, lid: int,
                     changed: np.ndarray) -> None:
        sg = self.sg
        c = int(sg.send_counts[p, d - 1])
        row_i = sg.send_idx[p, d - 1]
        row_m = sg.send_mask[p, d - 1]
        k = int(np.searchsorted(row_i[:c], lid))
        row_i[k + 1:c + 1] = row_i[k:c]
        row_m[k + 1:c + 1] = row_m[k:c]
        row_i[k] = lid
        row_m[k] = True
        sg.send_counts[p, d - 1] = c + 1
        changed[p, d - 1, k:c + 1] = True

    def _send_remove(self, p: int, d: int, lid: int,
                     changed: np.ndarray) -> None:
        sg = self.sg
        c = int(sg.send_counts[p, d - 1])
        row_i = sg.send_idx[p, d - 1]
        row_m = sg.send_mask[p, d - 1]
        k = int(np.searchsorted(row_i[:c], lid))
        if k >= c or row_i[k] != lid:
            raise AssertionError(
                f"send-list entry for local {lid} missing on "
                f"(part {p}, dist {d})")
        row_i[k:c - 1] = row_i[k + 1:c]
        row_m[k:c - 1] = row_m[k + 1:c]
        # zeroed tail matches _send_structures' np.zeros initialization
        row_i[c - 1] = 0
        row_m[c - 1] = False
        sg.send_counts[p, d - 1] = c - 1
        changed[p, d - 1, k:c] = True

    # ---------------- device edge recompute ---------------------------

    def _rebuild_device_edges(self, q: int) -> None:
        """Recompute shard q's padded edge arrays from the host COO —
        build()'s exact localization and stable CSR-by-dst sort
        restricted to one owner, so the result is bit-identical to a
        full rebuild's shard q."""
        g, sg, P = self.g, self.sg, self.P
        n_max, b_max, e_max = sg.n_max, sg.b_max, sg.e_max
        own = np.flatnonzero(self.parts[g.dst] == q)
        if own.size > e_max:
            raise AssertionError(
                f"shard {q} edge count {own.size} > e_max {e_max} "
                "after capacity check")
        srcg = g.src[own]
        dstl = self.local_id[g.dst[own]]
        p_src = self.parts[srcg]
        lid = self.local_id[srcg]
        src_local = np.where(p_src == q, lid, -1)
        for p in range(P):
            if p == q:
                continue
            m = p_src == p
            if not m.any():
                continue
            d = (q - p) % P
            cnt = int(sg.send_counts[p, d - 1])
            rank = np.searchsorted(sg.send_idx[p, d - 1, :cnt], lid[m])
            if np.any(rank >= cnt) or np.any(
                    sg.send_idx[p, d - 1, rank] != lid[m]):
                raise AssertionError(
                    f"cross edge source missing from ({p}, d={d}) "
                    "send list")
            src_local[m] = n_max + (d - 1) * b_max + rank
        order = np.argsort(dstl, kind="stable")
        cnt_e = own.size
        sg.edge_src[q, :cnt_e] = src_local[order].astype(np.int32)
        sg.edge_dst[q, :cnt_e] = dstl[order].astype(np.int32)
        sg.edge_src[q, cnt_e:] = 0
        sg.edge_dst[q, cnt_e:] = n_max
        sg.edge_count[q] = cnt_e
