"""Write-ahead delta journal: crash-consistent streaming topology.

PR 13 made the graph live (stream/deltas.py + stream/patch.py) but left
a durability hole: a kill between a delta apply and the next checkpoint
silently reverts topology on resume — the checkpoint holds params that
trained AGAINST the post-delta graph while the resumed process rebuilds
the nominal one. This module closes the hole with a WAL:

  * every applied ``DeltaBatch`` is journaled BEFORE it is applied
    (WAL-first), as one CRC-guarded JSONL record carrying the batch
    payload plus the ``topo_generation`` the apply produced;
  * records accumulate in segment files ``journal-<firstseq>.jsonl``
    (header line pins format + version); segments rotate at
    ``segment_max_records`` and new segments are born atomically
    (header written via ``write_text_atomic``) so a torn rotation never
    leaves a headerless file;
  * checkpoints stamp a watermark (``__stream_seq__`` = last applied
    seq, ``__topo_generation__``) — resume rebuilds the nominal graph,
    replays every journaled seq <= watermark through the patcher, then
    truncates the journal after the watermark (classic WAL rollback of
    uncommitted entries: the StreamPlan re-delivers them at their
    scheduled epochs, reproducing the uninterrupted trajectory
    bitwise);
  * the newest segment's tail is torn-tolerant: a half-written last
    line (crash mid-append, or the ``journal-torn`` fault drill) is
    dropped at scan time and the lost suffix re-derived from the plan;
    a bad record anywhere ELSE is real corruption and raises
    :class:`JournalCorrupt` loudly;
  * degrade-not-lose: an append that hits the armed ``FaultyIO`` seams
    (ENOSPC / ro-dir / torn-write) queues the batch in an in-memory
    pending list INSTEAD of applying it — order is preserved, nothing
    is applied that is not durable, and the trainer drains the queue at
    later epoch boundaries once the disk recovers (same policy family
    as the membership ledger and the metrics sink).

The bit-identity oracle from tests/test_stream.py is packaged here as
:func:`verify_against_rebuild` so every resume path (trainer CLI, soak
invariant #9, serving replicas) can prove "replayed tables == a
from-scratch build of the post-delta graph" with one call.
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..resilience.storage import FAULTY_IO, FaultyIO, write_text_atomic
from .deltas import DeltaBatch, StreamPlan, _canon_payload, _json_crc

JOURNAL_FORMAT_VERSION = 1
_FORMAT_NAME = "pipegcn-journal"
_SEG_RE = re.compile(r"^journal-(\d{8})\.jsonl$")

# journal record "op" vocabulary for obs/schema.py `journal` records
# (emitted by the trainer / CLI, not by this module — listed here so
# the writer and the schema agree on one source of truth)
JOURNAL_OPS = ("append", "replay", "rotate", "truncate", "degraded",
               "recovered", "skew", "watermark", "verify")


class JournalCorrupt(RuntimeError):
    """A journal segment failed validation beyond the tolerated torn
    tail (bad header, CRC mismatch in a sealed segment, seq regression
    across records)."""


# ---------------------------------------------------------------------
# record (de)serialization
# ---------------------------------------------------------------------

def _record_line(batch: DeltaBatch, topo_generation: int) -> str:
    payload = _canon_payload(batch)
    payload["topo_generation"] = int(topo_generation)
    payload["crc"] = _json_crc(payload)
    return json.dumps(payload, sort_keys=True)


def _parse_record(rec: dict) -> Tuple[int, DeltaBatch]:
    gen = int(rec.pop("topo_generation", 0))
    multilabel = bool(rec.pop("node_label_multilabel", False))
    feat = rec["node_feat"]
    nf = (np.asarray(feat, np.float32).reshape(len(feat), -1)
          if feat else None)
    nl = rec["node_label"]
    label = (np.asarray(nl, np.float32 if multilabel else np.int64)
             if nl else None)
    b = DeltaBatch.make(rec["seq"], rec["add_edges"], rec["del_edges"],
                        nf, label, tuple(rec["node_nbrs"]))
    return gen, b


def _segment_name(first_seq: int) -> str:
    return f"journal-{first_seq:08d}.jsonl"


def _header_line(first_seq: int) -> str:
    hdr = {"format": _FORMAT_NAME, "version": JOURNAL_FORMAT_VERSION,
           "first_seq": int(first_seq)}
    hdr["crc"] = _json_crc(hdr)
    return json.dumps(hdr, sort_keys=True)


# ---------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------

class DeltaJournal:
    """Append-only, CRC-chunked, segment-rotated WAL of applied
    ``DeltaBatch``es.

    Thread-unsafe by design (the trainer touches it from the epoch loop
    only; serving replicas replay before their serve threads start).
    """

    def __init__(self, directory: str, *, segment_max_records: int = 256,
                 fsync: bool = False, io: Optional[FaultyIO] = None):
        self.directory = directory
        self.segment_max_records = int(segment_max_records)
        self.fsync = bool(fsync)
        self._io = io if io is not None else FAULTY_IO
        # (batch, topo_generation) appends that could not be made
        # durable, in arrival order — degrade-not-lose
        self.pending: List[Tuple[DeltaBatch, int]] = []
        os.makedirs(directory, exist_ok=True)
        self._seg_path: Optional[str] = None   # newest segment
        self._seg_records = 0                  # good records in it
        self._last_seq = -1
        self._last_gen = 0
        self._rescan()

    # -- scanning ------------------------------------------------------

    def _segments(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort()
        return out

    def _scan_segment(self, path: str, *, newest: bool
                      ) -> List[Tuple[int, DeltaBatch]]:
        """Parse one segment. In the NEWEST segment a trailing bad /
        partial line is a torn tail: tolerated, good prefix kept. In a
        sealed segment any bad line is corruption."""
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
        lines = raw.split("\n")
        if lines and lines[-1] == "":
            lines.pop()
        if not lines:
            raise JournalCorrupt(f"{path}: empty segment (no header)")
        try:
            hdr = json.loads(lines[0])
            crc = hdr.pop("crc")
            ok = (_json_crc(hdr) == crc
                  and hdr.get("format") == _FORMAT_NAME
                  and hdr.get("version") == JOURNAL_FORMAT_VERSION)
        except (ValueError, KeyError, TypeError):
            ok = False
        if not ok:
            raise JournalCorrupt(
                f"{path}: bad or version-skewed header — refusing to "
                f"replay through it")
        entries: List[Tuple[int, DeltaBatch]] = []
        for i, line in enumerate(lines[1:], start=2):
            try:
                rec = json.loads(line)
                crc = rec.pop("crc")
                if _json_crc(rec) != crc:
                    raise ValueError("crc mismatch")
                gen, b = _parse_record(rec)
            except (ValueError, KeyError, TypeError, IndexError) as exc:
                if newest and i == len(lines):
                    break  # torn tail: drop the partial record
                raise JournalCorrupt(
                    f"{path}:{i}: corrupt journal record ({exc}) in a "
                    f"sealed position — not a torn tail") from exc
            entries.append((gen, b))
        return entries

    def _rescan(self) -> None:
        segs = self._segments()
        self._seg_path = segs[-1][1] if segs else None
        self._seg_records = 0
        self._last_seq = -1
        self._last_gen = 0
        for _, path in segs:
            newest = path == self._seg_path
            entries = self._scan_segment(path, newest=newest)
            if newest:
                self._seg_records = len(entries)
                self._heal_torn_tail()
            for gen, b in entries:
                if b.seq <= self._last_seq:
                    raise JournalCorrupt(
                        f"{path}: seq {b.seq} after {self._last_seq} — "
                        f"journal is not monotonic")
                self._last_seq, self._last_gen = b.seq, gen

    def _heal_torn_tail(self) -> None:
        """A crash mid-append leaves the newest segment ending in a
        partial line with no terminator; a later append would weld its
        record onto that garbage, silently losing a durable-looking
        write. Rewrite the segment down to its good prefix (header +
        ``_seg_records`` good lines) before anyone appends."""
        path = self._seg_path
        if path is None or not os.path.exists(path):
            return
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
        lines = raw.split("\n")
        if lines and lines[-1] == "":
            lines.pop()
        good = "\n".join(lines[:1 + self._seg_records]) + "\n"
        if good != raw:
            with open(path, "w", encoding="utf-8") as f:
                f.write(good)

    # -- reading -------------------------------------------------------

    def entries(self) -> List[Tuple[int, DeltaBatch]]:
        """All good (topo_generation, batch) records in seq order,
        torn-tail tolerant."""
        out: List[Tuple[int, DeltaBatch]] = []
        segs = self._segments()
        for _, path in segs:
            out.extend(self._scan_segment(
                path, newest=(path == segs[-1][1])))
        return out

    def replay(self, up_to_seq: Optional[int] = None
               ) -> List[Tuple[int, DeltaBatch]]:
        """Entries with seq <= up_to_seq (all when None)."""
        es = self.entries()
        if up_to_seq is None:
            return es
        return [(g, b) for g, b in es if b.seq <= up_to_seq]

    def last_seq(self) -> int:
        return self._last_seq

    def last_generation(self) -> int:
        return self._last_gen

    @property
    def pending_count(self) -> int:
        return len(self.pending)

    # -- writing -------------------------------------------------------

    def _append_durable(self, batch: DeltaBatch,
                        topo_generation: int) -> None:
        """Raises OSError on any seam failure; on success the record is
        on disk (fsync'd when configured)."""
        rotate = (self._seg_path is None
                  or self._seg_records >= self.segment_max_records)
        if rotate:
            path = os.path.join(self.directory,
                                _segment_name(max(batch.seq, 0)))
            # atomic birth: the header lands via temp+rename, so a torn
            # rotation leaves no headerless segment behind
            write_text_atomic(path, _header_line(batch.seq) + "\n",
                              fsync=self.fsync, io=self._io)
            self._seg_path, self._seg_records = path, 0
        path = self._seg_path
        self._io.gate(path, "open")
        with open(path, "a", encoding="utf-8") as f:
            self._io.gate(path, "write")
            f.write(_record_line(batch, topo_generation) + "\n")
            f.flush()
            if self.fsync:
                self._io.gate(path, "fsync")
                os.fsync(f.fileno())
        self._seg_records += 1
        self._last_seq = int(batch.seq)
        self._last_gen = int(topo_generation)

    def append(self, batch: DeltaBatch, topo_generation: int) -> bool:
        """Journal one batch. True = durable now; False = the disk is
        degraded and the batch joined the pending queue (caller must
        NOT apply it yet — WAL-first means un-journaled changes never
        reach the topology)."""
        if self.pending:
            # order preservation: nothing overtakes a queued batch
            self.pending.append((batch, int(topo_generation)))
            return False
        try:
            self._append_durable(batch, topo_generation)
            return True
        except OSError:
            self.pending.append((batch, int(topo_generation)))
            return False

    def drain_pending(self) -> List[Tuple[DeltaBatch, int]]:
        """Retry queued appends in order; returns the batches that just
        became durable (the caller applies them now). Stops at the
        first append that still fails."""
        drained: List[Tuple[DeltaBatch, int]] = []
        while self.pending:
            batch, gen = self.pending[0]
            try:
                self._append_durable(batch, gen)
            except OSError:
                break
            self.pending.pop(0)
            drained.append((batch, gen))
        return drained

    # -- rollback / fault hooks ---------------------------------------

    def truncate_after(self, seq: int) -> int:
        """WAL rollback: drop every record with seq > `seq` (entries
        past the checkpoint watermark are uncommitted — the StreamPlan
        re-delivers them at their scheduled epochs). Segments are
        rewritten atomically. Returns the number of records dropped."""
        keep: List[Tuple[int, DeltaBatch]] = []
        dropped = 0
        segs = self._segments()
        for _, path in segs:
            for gen, b in self._scan_segment(
                    path, newest=(path == segs[-1][1])):
                if b.seq <= seq:
                    keep.append((gen, b))
                else:
                    dropped += 1
        if dropped == 0:
            return 0
        for _, path in segs:
            os.remove(path)
        self._seg_path = None
        self._seg_records = 0
        self._last_seq = -1
        self._last_gen = 0
        for i in range(0, len(keep), self.segment_max_records):
            chunk = keep[i:i + self.segment_max_records]
            lines = [_header_line(chunk[0][1].seq)]
            lines += [_record_line(b, g) for g, b in chunk]
            path = os.path.join(self.directory,
                                _segment_name(chunk[0][1].seq))
            write_text_atomic(path, "\n".join(lines) + "\n",
                              fsync=self.fsync, io=self._io)
            self._seg_path, self._seg_records = path, len(chunk)
        if keep:
            self._last_seq = int(keep[-1][1].seq)
            self._last_gen = int(keep[-1][0])
        return dropped

    def tear_newest_segment(self) -> int:
        """Fault-drill hook (``journal-torn@E``): truncate the newest
        segment file to half its bytes, exactly like an interrupted
        append. Returns the number of records lost (recovery walks back
        to the surviving prefix and re-derives the rest from the
        plan)."""
        if self._seg_path is None or not os.path.exists(self._seg_path):
            return 0
        before = len(self._scan_segment(self._seg_path, newest=True))
        size = os.path.getsize(self._seg_path)
        with open(self._seg_path, "r+b") as f:
            f.truncate(size // 2)
        try:
            after = len(self._scan_segment(self._seg_path, newest=True))
        except JournalCorrupt:
            # header itself torn: the segment is gone entirely
            os.remove(self._seg_path)
            after = 0
        self._rescan()
        return before - after


# ---------------------------------------------------------------------
# replay + verification helpers (shared by trainer CLI, soak, serving)
# ---------------------------------------------------------------------

def replay_for_resume(journal: DeltaJournal, watermark_seq: int,
                      apply_fn: Callable[[DeltaBatch], object], *,
                      plan: Optional[StreamPlan] = None,
                      ) -> Dict[str, int]:
    """Bring a freshly-rebuilt NOMINAL graph to the state the
    checkpointed params trained against: apply every seq <=
    `watermark_seq`, preferring the journal's copy and falling back to
    the plan's delta files for seqs the journal lost (torn tail /
    ``journal-torn`` drill). Then roll the journal back past the
    watermark (uncommitted entries — the plan re-delivers them live).

    Returns ``{"replayed", "rederived", "truncated", "skipped",
    "topo_generation"}``.
    """
    journaled = {b.seq: (g, b) for g, b in journal.replay(watermark_seq)}
    planned: Dict[int, DeltaBatch] = {}
    if plan is not None:
        planned = {b.seq: b for b in plan.batches_upto(watermark_seq)}
    seqs = sorted(set(journaled) | set(planned))
    replayed = rederived = skipped = 0
    gen = 0
    for s in seqs:
        if s in journaled:
            g, b = journaled[s]
            apply_fn(b)
            replayed += 1
            gen = g
        elif s in planned:
            apply_fn(planned[s])
            rederived += 1
            gen += 1
        else:  # pragma: no cover — unreachable (s from the union)
            skipped += 1
    truncated = journal.truncate_after(watermark_seq)
    return {"replayed": replayed, "rederived": rederived,
            "truncated": truncated, "skipped": skipped,
            "topo_generation": gen}


_VERIFY_ARRAYS = ("inner_count", "train_count", "edge_count",
                  "send_counts", "edge_src", "edge_dst", "send_idx",
                  "send_mask", "feat", "label", "train_mask",
                  "val_mask", "test_mask", "in_deg", "global_nid")


def verify_against_rebuild(patcher) -> Dict[str, object]:
    """The bit-identity oracle as a callable check: rebuild the sharded
    tables from scratch out of the patcher's CURRENT graph + partition
    map at the same padded dims, and compare every device table
    bitwise. Returns ``{"tables_match": bool, "mismatch": [names]}``.
    """
    from ..partition.halo import ShardedGraph

    sg = patcher.sg
    sg2 = ShardedGraph.build(patcher.g, patcher.parts,
                             n_parts=sg.num_parts,
                             min_n_max=sg.n_max, min_b_max=sg.b_max,
                             min_e_max=sg.e_max)
    mismatch = []
    for name in _VERIFY_ARRAYS:
        a = np.asarray(getattr(sg, name))
        b = np.asarray(getattr(sg2, name))
        if a.shape != b.shape or a.dtype != b.dtype \
                or not np.array_equal(a, b):
            mismatch.append(name)
    return {"tables_match": not mismatch, "mismatch": mismatch}
