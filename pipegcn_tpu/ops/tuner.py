"""Shape-aware SpMM kernel auto-tuner: measured cost tables, not guesses.

The hand-tuned ``auto`` thresholds this replaces (edge-count and
dense-coverage cutoffs in ``parallel/trainer.py``) were invalidated by
the very first second shape they met (the products-shape block-kernel
crash). This module instead *times* each viable kernel configuration —
{sorted-XLA, bucket, block} x remainder transport dtype
{none, bf16, fp8, fp8+amax} x block group size — on a sampled slice of
the real degree distribution, and persists the winner plus the full
measured cost table into the partition artifact (``tuning.json``
sidecar, valid for both the v2 npz and v3 mmap directory formats).

Sampling keeps the *shape* the kernels are sensitive to: destination
rows are drawn uniformly but each keeps its FULL in-edge list, so the
sampled in-degree distribution matches the shard's. The per-SpMM cost
is scaled back by full_edges / sample_edges for reporting; the argmin
is taken on the measured numbers directly.

Timing follows the microbench idiom (scripts/spmm_microbench.py):
tables ride as jit ARGUMENTS, never closure constants (closed-over
arrays embed into the HLO, and the remote-compile tunnel rejects
GB-sized HTTP bodies), and every sample forces a device->host scalar
read (`float(jnp.sum(...))`) because `block_until_ready` alone does
not synchronize through the tunnel.

Staleness: a persisted table is trusted only when its tuner format,
source-graph edge checksum AND config signature (backend, feature
width, tile, bucket-merge, chunk) all match. Any mismatch is returned
as a human-readable reason so the caller can re-tune live WITH A LOUD
RECORD instead of silently dispatching from a rotted table.
"""

from __future__ import annotations

import json
import os
import time
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

TUNER_FORMAT = 1
TUNING_FILE = "tuning.json"

# destination-row sampling stops once this many edges are covered; the
# CLI surfaces it as --tuner-samples
DEFAULT_EDGE_BUDGET = 200_000

# deterministic no-measurement fallback: the scatter-free bucket kernel
# is in-domain at every shard size (unlike block, which needs a dense
# tile structure worth the table bytes). Used when tuning is disabled
# and no persisted table exists, and when every candidate errors. This
# is a fixed preference order, NOT a shape threshold.
DEFAULT_IMPL = "bucket"

# SpMM invocations per epoch of the 4-layer use_pp bench stack: 3 graph
# layers, each one forward + one backward aggregation
_SPMM_PER_EPOCH = 3

# in-process memo of live tuning runs keyed by (checksum, signature):
# tests and repeated trainer constructions over the same artifact must
# not re-pay ~a dozen candidate compiles each time
_MEMO: Dict[Tuple, Dict[str, Any]] = {}


def clear_memo() -> None:
    """Drop the in-process live-tune memo (test isolation hook)."""
    _MEMO.clear()


# ---------------------------------------------------------------------
# sampling


def sample_slice(sg, edge_budget: int = DEFAULT_EDGE_BUDGET,
                 seed: int = 0):
    """A 1-part ShardedGraph-shaped view of the heaviest shard's edges.

    Destination rows are sampled uniformly, each keeping its full
    in-edge list, until `edge_budget` edges are covered — preserving
    the in-degree distribution the bucket ladder and the block tiling
    both key on. Row ids are compacted (sampled destinations first, so
    every dst id < n_max; remaining source rows follow) and the result
    quacks like a ShardedGraph for the sharded table builders:
    num_parts=1, halo_size=0, all rows inner.

    Returns (sample, info) where info carries sample_edges /
    full_edges / scale.
    """
    r = int(np.argmax(np.asarray(sg.edge_count)))
    ec = int(sg.edge_count[r])
    es = np.asarray(sg.edge_src[r][:ec], dtype=np.int64)
    ed = np.asarray(sg.edge_dst[r][:ec], dtype=np.int64)
    real = ed < sg.n_max
    es, ed = es[real], ed[real]
    full_edges = int(np.sum(np.asarray(sg.edge_count)))

    if es.size > edge_budget:
        deg = np.bincount(ed, minlength=sg.n_max)
        rows = np.flatnonzero(deg > 0)
        rng = np.random.default_rng(seed)
        rng.shuffle(rows)
        cum = np.cumsum(deg[rows])
        n_keep = max(1, int(np.searchsorted(cum, edge_budget) + 1))
        chosen = rows[:n_keep]
        sel = np.zeros(sg.n_max, dtype=bool)
        sel[chosen] = True
        keep = sel[ed]
        es, ed = es[keep], ed[keep]
    else:
        chosen = np.unique(ed)

    # compact ids: sampled destinations first, then the remaining
    # source rows (halo slots and unsampled inner rows alike)
    chosen = np.sort(chosen)
    n_dst = int(chosen.size)
    src_space = sg.n_max + sg.halo_size
    remap = np.full(src_space, -1, dtype=np.int64)
    remap[chosen] = np.arange(n_dst)
    extra = np.unique(es[remap[es] < 0])
    remap[extra] = n_dst + np.arange(extra.size)
    n_rows = n_dst + int(extra.size)

    new_src = remap[es].astype(np.int32)
    new_dst = remap[ed].astype(np.int32)
    # CSR order (dst ascending) so the sorted-XLA candidate times the
    # same formulation the trainer dispatches
    order = np.argsort(new_dst, kind="stable")
    new_src, new_dst = new_src[order], new_dst[order]

    in_deg = np.maximum(
        np.bincount(new_dst, minlength=n_rows), 1).astype(np.float32)

    sample = SimpleNamespace(
        num_parts=1, n_max=n_rows, b_max=0, halo_size=0,
        e_max=int(new_src.size),
        edge_count=np.array([new_src.size], dtype=np.int64),
        edge_src=new_src[None, :], edge_dst=new_dst[None, :],
        in_deg=in_deg[None, :], n_feat=getattr(sg, "n_feat", 0),
        cache_dir=None,
    )
    info = {
        "sample_edges": int(new_src.size),
        "sample_rows": n_rows,
        "full_edges": full_edges,
        "scale": full_edges / max(1, int(new_src.size)),
        "sampled_rank": r,
    }
    return sample, info


# ---------------------------------------------------------------------
# candidate grid


def candidate_grid(*, block_group: int = 0,
                   rem_dtype: str = "auto",
                   rem_amax: bool = False,
                   slab: str = "auto") -> List[Dict[str, Any]]:
    """Viable kernel configs to time. An explicitly-pinned transport
    dtype (`rem_dtype` other than "auto") or group size (`block_group`
    > 1) restricts the grid to the pinned value — the tuner never
    overrides an explicit user choice, it only fills defaults.

    `slab` extends the grid with the streaming-slab gather path
    (bucket_spmm build_slab_plan): "auto" adds one measured slab twin
    per kernel family (on the first transport variant — a full slab x
    transport cross product would double the compile bill for a
    row-structure lever that is independent of the cast); "on"/"off"
    pin every candidate."""
    if rem_dtype == "auto":
        rems = [(None, False), ("bfloat16", False), ("float8", False),
                ("float8", True)]
    else:
        rems = [(rem_dtype, rem_amax)]
    groups = [block_group] if block_group and block_group > 1 else [1, 4]
    pin_slab = {"on": True, "off": False}.get(slab)
    base_slab = bool(pin_slab)

    def name(impl, rd, ra, g, sl=False):
        parts = [impl]
        if impl == "block" and g > 1:
            parts.append(f"u{g}")
        if rd == "bfloat16":
            parts.append("bf16")
        elif rd == "float8":
            parts.append("f8amax" if ra else "f8")
        if sl:
            parts.append("slab")
        return "-".join(parts)

    cands = [{"name": "xla", "impl": "xla", "rem_dtype": None,
              "rem_amax": False, "block_group": 1, "slab": False}]
    for i, (rd, ra) in enumerate(rems):
        slabs = [base_slab]
        if pin_slab is None and i == 0:
            slabs = [False, True]
        for sl in slabs:
            cands.append({"name": name("bucket", rd, ra, 1, sl),
                          "impl": "bucket", "rem_dtype": rd,
                          "rem_amax": ra, "block_group": 1, "slab": sl})
    for i, (rd, ra) in enumerate(rems):
        for g in groups:
            slabs = [base_slab]
            if pin_slab is None and i == 0:
                slabs = [False, True]
            for sl in slabs:
                cands.append({"name": name("block", rd, ra, g, sl),
                              "impl": "block", "rem_dtype": rd,
                              "rem_amax": ra, "block_group": g,
                              "slab": sl})
    return cands


# ---------------------------------------------------------------------
# timing


def _time_candidate(sample, cand: Dict[str, Any], width: int, *,
                    block_tile: int, block_nnz: Optional[int],
                    chunk_edges: Optional[int], bucket_merge: int,
                    reps: int) -> float:
    """Measured seconds for ONE forward+backward SpMM of this candidate
    on the sample (min over reps). Raises on kernel failure — the
    caller records the error in the cost table."""
    import jax
    import jax.numpy as jnp

    n_max = sample.n_max
    n_src = n_max  # 1-part sample: halo_size == 0, all rows inner
    rng = np.random.default_rng(0)
    fbuf = jnp.asarray(
        rng.standard_normal((n_src, width)).astype(np.float32)
    ).astype(jnp.bfloat16)
    in_deg = jnp.asarray(sample.in_deg[0])

    impl = cand["impl"]
    if impl == "xla":
        from .spmm import spmm_mean

        es = jnp.asarray(sample.edge_src[0])
        ed = jnp.asarray(sample.edge_dst[0])

        def apply(tabs, deg, f):
            return spmm_mean(f, tabs["es"], tabs["ed"], deg, n_max,
                             chunk=chunk_edges, sorted_edges=True)

        tabs = {"es": es, "ed": ed}
    elif impl == "bucket":
        from .bucket_spmm import (build_sharded_bucket_tables,
                                  make_device_bucket_spmm_fn)

        tables = build_sharded_bucket_tables(
            sample, min_width=bucket_merge,
            slab=bool(cand.get("slab")))
        tabs = {k: jnp.asarray(v[0]) for k, v in tables.items()}

        def apply(tabs, deg, f):
            fn = make_device_bucket_spmm_fn(
                tabs, deg, n_src, chunk_edges=chunk_edges,
                rem_dtype=cand["rem_dtype"], rem_amax=cand["rem_amax"])
            return fn(f)
    elif impl == "block":
        from .block_spmm import (build_sharded_block_tables,
                                 make_device_block_spmm_fn)

        tables, tile = build_sharded_block_tables(
            sample, tile=block_tile, n_feat_hint=width,
            nnz_threshold=block_nnz, group=cand["block_group"],
            slab=bool(cand.get("slab")))
        tabs = {k: jnp.asarray(v[0]) for k, v in tables.items()}

        def apply(tabs, deg, f):
            fn = make_device_block_spmm_fn(
                tabs, deg, n_max, n_src, tile, chunk_edges=chunk_edges,
                rem_dtype=cand["rem_dtype"], rem_amax=cand["rem_amax"])
            return fn(f)
    else:
        raise ValueError(f"unknown tuner candidate impl {impl!r}")

    grad_fn = jax.jit(lambda t, deg, f: jax.grad(
        lambda ff: apply(t, deg, ff).astype(jnp.float32).sum())(f))
    float(jnp.sum(grad_fn(tabs, in_deg, fbuf)))  # compile + settle
    ts = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        float(jnp.sum(grad_fn(tabs, in_deg, fbuf)))
        ts.append(time.perf_counter() - t0)
    return min(ts)


# ---------------------------------------------------------------------
# the tuner


def signature_for(*, width: int, block_tile: int, bucket_merge: int,
                  chunk_edges: Optional[int],
                  rng_impl: str = "threefry",
                  halo_dtype: str = "none",
                  epoch_block: int = 0,
                  reorder: str = "none",
                  layout_version: int = 1) -> Dict[str, Any]:
    """Config signature a persisted table must match to be trusted.
    Backend is part of it: CPU timings say nothing about the TPU. The
    floor-lever knobs (rng_impl / halo_dtype / epoch_block) are part of
    it too: they reshape the step program around the SpMM, so a cost
    table measured under one lever setting must not silently pick
    kernels for another. So are the artifact's node layout
    (reorder/layout_version): a cost table measured on the pre-reorder
    gather streams must not pick kernels for the reordered ones.
    Tables persisted before these keys existed mismatch (exact-dict
    compare) and re-tune once — deliberate; the keyword defaults match
    TrainConfig's / pre-reorder artifacts' for older call sites."""
    import jax

    return {
        "backend": jax.default_backend(),
        "width": int(width),
        "block_tile": int(block_tile),
        "bucket_merge": int(bucket_merge),
        "chunk_edges": int(chunk_edges) if chunk_edges else 0,
        "rng_impl": str(rng_impl or "threefry"),
        "halo_dtype": str(halo_dtype or "none"),
        "epoch_block": int(epoch_block or 0),
        "reorder": str(reorder or "none"),
        "layout_version": int(layout_version or 1),
    }


def tune(sg, width: int, *, block_tile: int = 256,
         block_nnz: Optional[int] = None, block_group: int = 0,
         rem_dtype: str = "auto", rem_amax: bool = False,
         chunk_edges: Optional[int] = None, bucket_merge: int = 0,
         rng_impl: str = "threefry", halo_dtype: str = "none",
         epoch_block: int = 0, slab: str = "auto",
         edge_budget: int = DEFAULT_EDGE_BUDGET, reps: int = 2,
         seed: int = 0,
         log: Optional[Callable[[str], None]] = None) -> Dict[str, Any]:
    """Run the micro-benchmark campaign and return the tuning record
    (winner + full measured cost table). Results are memoized
    in-process by (source checksum, signature, budget) so repeated
    trainer constructions over the same artifact pay once."""
    sig = signature_for(width=width, block_tile=block_tile,
                        bucket_merge=bucket_merge,
                        chunk_edges=chunk_edges,
                        rng_impl=rng_impl, halo_dtype=halo_dtype,
                        epoch_block=epoch_block,
                        reorder=getattr(sg, "reorder", "none"),
                        layout_version=getattr(sg, "layout_version", 1))
    checksum = int(getattr(sg, "source_edge_checksum", -1)) \
        & ((1 << 64) - 1)
    memo_key = (checksum, json.dumps(sig, sort_keys=True),
                int(edge_budget), int(block_group),
                str(rem_dtype), bool(rem_amax), str(slab))
    hit = _MEMO.get(memo_key)
    if hit is not None:
        return hit

    sample, info = sample_slice(sg, edge_budget=edge_budget, seed=seed)
    cands = candidate_grid(block_group=block_group, rem_dtype=rem_dtype,
                           rem_amax=rem_amax, slab=slab)
    costs: List[Dict[str, Any]] = []
    for cand in cands:
        entry = dict(cand)
        try:
            s = _time_candidate(
                sample, cand, width, block_tile=block_tile,
                block_nnz=block_nnz, chunk_edges=chunk_edges,
                bucket_merge=bucket_merge, reps=reps)
            entry["spmm_fwdbwd_s"] = s
            entry["est_epoch_spmm_s"] = round(
                s * info["scale"] * _SPMM_PER_EPOCH, 6)
            entry["error"] = None
            if log:
                log(f"# tuner: {cand['name']:16s} {s * 1e3:8.2f} ms "
                    f"(est epoch SpMM "
                    f"{entry['est_epoch_spmm_s']:.3f} s)")
        except Exception as exc:  # noqa: BLE001 — a crashing candidate
            # is a RESULT (out-of-domain config), not a tuner failure
            entry["spmm_fwdbwd_s"] = None
            entry["est_epoch_spmm_s"] = None
            entry["error"] = repr(exc)[:200]
            if log:
                log(f"# tuner: {cand['name']:16s} FAILED: "
                    f"{entry['error']}")
        costs.append(entry)

    ok = [c for c in costs if c["error"] is None]
    if ok:
        best = min(ok, key=lambda c: c["spmm_fwdbwd_s"])
    else:
        best = {"name": DEFAULT_IMPL, "impl": DEFAULT_IMPL,
                "rem_dtype": None, "rem_amax": False, "block_group": 1,
                "slab": False}
    # the sample's gather-contiguity stat rides in the record: the
    # number the reorder lever is supposed to move, next to the
    # measured winner it produced (host numpy on the sample tables —
    # noise next to the candidate compiles)
    try:
        from .bucket_spmm import (build_sharded_bucket_tables,
                                  gather_contiguity)
        contig = gather_contiguity(
            build_sharded_bucket_tables(sample), sample.n_max)
    except Exception:  # noqa: BLE001 — a stat, never a tuner failure
        contig = None
    record = {
        "tuner_format": TUNER_FORMAT,
        "source_edge_checksum": checksum,
        "signature": sig,
        "winner": {k: best.get(k, False) for k in
                   ("name", "impl", "rem_dtype", "rem_amax",
                    "block_group", "slab")},
        "costs": costs,
        "reps": int(reps),
        "gather_contiguity": contig,
        "time_unix": time.time(),
        **info,
    }
    _MEMO[memo_key] = record
    return record


# ---------------------------------------------------------------------
# persistence (tuning.json sidecar in the artifact directory)


def tuning_path(cache_dir: str) -> str:
    return os.path.join(cache_dir, TUNING_FILE)


def save_tuning(cache_dir: str, record: Dict[str, Any]) -> None:
    """Atomically persist the tuning record next to the artifact's
    npz/mmap payload (both formats are directories, so the sidecar
    rides along for free and versions with the artifact). Routed
    through the storage-fault seams (resilience/storage.py): a torn or
    failed write leaves the previous sidecar — or nothing — and
    load_tuning's never-raise contract degrades to a live re-tune."""
    from ..resilience.storage import write_text_atomic

    write_text_atomic(tuning_path(cache_dir),
                      json.dumps(record, indent=1), fsync=False)


def load_tuning(cache_dir: str, *,
                expect_checksum: Optional[int] = None,
                signature: Optional[Dict[str, Any]] = None,
                ) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
    """(record, None) when the persisted table is present AND trusted;
    (None, reason) otherwise. Never raises: a corrupt sidecar must
    degrade to a live re-tune, not kill trainer setup."""
    path = tuning_path(cache_dir)
    if not os.path.exists(path):
        return None, "missing"
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as exc:
        return None, f"corrupt: {exc!r}"[:200]
    if not isinstance(rec, dict):
        return None, "corrupt: not a JSON object"
    if rec.get("tuner_format") != TUNER_FORMAT:
        return None, (f"format {rec.get('tuner_format')!r} != "
                      f"{TUNER_FORMAT}")
    w = rec.get("winner")
    if not isinstance(w, dict) or w.get("impl") not in (
            "xla", "bucket", "block"):
        return None, f"corrupt winner: {w!r}"[:200]
    if expect_checksum is not None:
        want = int(expect_checksum) & ((1 << 64) - 1)
        if rec.get("source_edge_checksum") != want:
            return None, ("stale: source_edge_checksum mismatch "
                          "(artifact rebuilt from a different graph)")
    if signature is not None and rec.get("signature") != signature:
        return None, (f"stale: signature {rec.get('signature')!r} != "
                      f"{signature!r}")[:300]
    return rec, None


# ---------------------------------------------------------------------
# --reorder auto resolution (measured, not a hand threshold)


def choose_reorder(g, *, modes: Tuple[str, ...] = ("none", "degree-bfs"),
                   edge_budget: int = DEFAULT_EDGE_BUDGET, reps: int = 2,
                   log: Optional[Callable[[str], None]] = None
                   ) -> Tuple[str, Dict[str, float]]:
    """Pick the artifact reorder mode for ``--reorder auto`` by
    MEASUREMENT: build a 1-part layout of ``g`` under each candidate
    mode, sample a degree-distribution-preserving slice, and time the
    bucket kernel's forward+backward on it — under the reordered
    layouts both with and without the streaming-slab plan (the path
    the reorder exists to enable), keeping each mode's best. Returns
    (winning mode, {mode: seconds}); an unmeasurable campaign (every
    candidate erroring) falls back to "none" — the layout every
    artifact already has."""
    from ..partition import ShardedGraph

    width = int(g.ndata["feat"].shape[-1]) if "feat" in g.ndata else 64
    parts = np.zeros(g.num_nodes, dtype=np.int32)
    timings: Dict[str, float] = {}
    for mode in modes:
        sg1 = ShardedGraph.build(g, parts, n_parts=1, reorder=mode)
        sample, _ = sample_slice(sg1, edge_budget=edge_budget)
        best = None
        for sl in ([False] if mode == "none" else [False, True]):
            cand = {"name": "bucket-slab" if sl else "bucket",
                    "impl": "bucket", "rem_dtype": None,
                    "rem_amax": False, "block_group": 1, "slab": sl}
            try:
                t = _time_candidate(sample, cand, width, block_tile=256,
                                    block_nnz=None, chunk_edges=None,
                                    bucket_merge=0, reps=reps)
            except Exception as exc:  # noqa: BLE001 — out-of-domain
                if log:
                    log(f"# choose_reorder: {mode} "
                        f"({cand['name']}) FAILED: {exc!r}"[:160])
                continue
            best = t if best is None else min(best, t)
        if best is not None:
            timings[mode] = round(best, 6)
            if log:
                log(f"# choose_reorder: {mode:10s} {best * 1e3:8.2f} ms")
    if not timings:
        return "none", timings
    return min(timings, key=timings.get), timings
