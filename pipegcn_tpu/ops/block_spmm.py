"""Hybrid block-dense SpMM: community-dense tiles on the MXU, sparse
remainder through the scatter-free bucket kernel.

The third TPU-native replacement for DGL's SpMM (reference
module/layer.py:47-49), aimed at the regime that actually decides the
headline benchmark: large community-structured graphs (Reddit-like).
Such graphs concentrate most edges in dense (destination-tile,
source-tile) blocks; a gather-based SpMM re-reads each source row
once per edge (~degree times), while a block-dense formulation reads
each participating feature tile once per block and turns the
aggregation into batched [T,S] @ [S,F] matmuls — exactly what the MXU
is for. Edges outside dense blocks (the uniform "background") fall
back to ops/bucket_spmm.py's gather + dense-reduction.

Traffic comparison per layer at Reddit scale (114M edges, F=256,
bf16): pure gather moves ~59 GB; with SBM-like structure the hybrid
moves ~2-4 GB of A-blocks + feature tiles plus the remainder's
gathers — an order of magnitude less, with the dense part's FLOPs
(~1 TFLOP) costing single-digit milliseconds on one v5e chip.

Mechanics:
  - Host tiles the destination space into rows of `tile` (T) and the
    source space into `tile` (S); (bd, bs) blocks with
    nnz * F >= T * S ("the dense A block is cheaper to read than the
    gathers it replaces") are materialized as dense [T, S] matrices
    holding per-edge 1.0 (duplicate edges accumulate).
  - Forward: per destination tile, sum_k A[blk_k] @ fbuf_tile[src_k]
    via one batched einsum inside a lax.scan over destination tiles.
  - Backward: the same A blocks, transposed roles — per SOURCE tile,
    sum_k A[blk_k]^T @ g_tile[dst_k] — so no scatter anywhere; the
    remainder's backward is the bucket kernel's transpose tables.
  - Mean normalization (in_deg division) is applied once at the end,
    after dense + remainder parts are summed.

All shapes are static; per-device plans pad to shared maxima
(block count, per-tile block lists, bucket caps) so a single traced
program serves every device in shard_map.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .bucket_spmm import (
    _bucket_widths,
    add_slab_plans,
    bucket_aggregate,
    build_tables_for_edges,
    extract_run_plans,
    ladder_prefix,
)

# HBM budget for the per-device dense-A tensor (see
# build_sharded_block_tables) — shared with estimate_block_coverage and
# the multichip projection so every consumer predicts the same spill.
DENSE_A_BYTE_BUDGET = 2 << 30


def budget_block_cap(byte_budget: int, tile: int, bits: int = 1) -> int:
    """Max dense A-blocks that fit `byte_budget` at `bits` per entry
    (1 = the optimistic bit-packed encoding for 0/1 graphs)."""
    return max(1, (int(byte_budget) * 8) // (tile * tile * bits))


def _pad_rows(mat: np.ndarray, rows: int, fill) -> np.ndarray:
    if mat.shape[0] == rows:
        return mat
    return np.pad(mat, ((0, rows - mat.shape[0]),) +
                  ((0, 0),) * (mat.ndim - 1), constant_values=fill)


def pack_a_blocks(a_blocks: np.ndarray) -> np.ndarray:
    """Bit-pack 0/1-valued dense blocks [B, T, S] -> uint8 [B, T, S//8].

    On simple graphs (edge multiplicity <= 1 — the common case after
    self-loop normalization) every A entry is 0 or 1, so one bit per
    entry suffices: 8x less HBM than int8, which buys 8x more dense
    blocks under the same byte budget. Little-endian bit order matches
    the device-side unpack in _dense_apply."""
    assert a_blocks.shape[-1] % 8 == 0, a_blocks.shape
    assert a_blocks.max(initial=0.0) <= 1.0, "bit-packing needs 0/1 A"
    return np.packbits(a_blocks.astype(bool), axis=-1, bitorder="little")


def _unpack_bits(blks: jax.Array, s: int, compute_dtype) -> jax.Array:
    """Device-side inverse of pack_a_blocks on gathered [..., T, S//8]
    uint8 blocks -> [..., T, S] in the compute dtype."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (blks[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(blks.shape[:-1] + (s,)).astype(compute_dtype)


def _max_group_count(keys: np.ndarray, n_groups: int) -> int:
    return max(int(np.bincount(keys, minlength=n_groups).max(initial=0)),
               1)


def _group_by_key(keys, vals_a, vals_b, n_groups, widths, pad_a, pad_b):
    """Bucket the (vals_a[i], vals_b[i]) pairs of each key into
    power-of-2 width classes by the key's pair count — the tile-level
    analogue of bucket_spmm's degree bucketing. A flat [n_groups, K_max]
    layout wastes (K_max - K_mean)/K_max of the dense path (measured 60%
    at Reddit scale: K_max 90 vs K_mean 36); per-width classes bound the
    padding at 2x and concentrate it in the cheap small-K classes.

    Returns (mats, inv, counts): mats[w] = (a_mat, b_mat), each
    [n_w, widths[w]] int32 padded with pad_a/pad_b; inv [n_groups] int32
    mapping each key to its row in the width-class concatenation (keys
    with no pairs -> sum(counts), the caller's zero sentinel row);
    counts[w] = real rows in class w."""
    order = np.argsort(keys, kind="stable")
    va, vb = vals_a[order], vals_b[order]
    cnt = np.bincount(keys, minlength=n_groups)
    # The fill mask truncates at each key's class width, so a ladder
    # whose top rung is below the max per-key count would silently drop
    # (A-block, tile) pairs. Fail loudly instead of aggregating wrong.
    max_cnt = int(cnt.max(initial=0))
    if max_cnt > widths[-1]:
        raise ValueError(
            f"width ladder {tuple(widths)} tops out below the max "
            f"per-key pair count {max_cnt}; pairs would be dropped")
    ptr = np.zeros(n_groups + 1, np.int64)
    np.cumsum(cnt, out=ptr[1:])
    widths_arr = np.asarray(widths, dtype=np.int64)
    wid = np.minimum(np.searchsorted(widths_arr, np.maximum(cnt, 1)),
                     len(widths) - 1)
    mats, counts = [], []
    inv = np.full(n_groups, -1, np.int64)
    offset = 0
    for w_i, w in enumerate(widths):
        rows = np.nonzero((wid == w_i) & (cnt > 0))[0]
        n_w = rows.shape[0]
        a_mat = np.full((n_w, w), pad_a, np.int32)
        b_mat = np.full((n_w, w), pad_b, np.int32)
        if n_w:
            j = np.arange(w)[None, :]
            mask = j < cnt[rows][:, None]
            pos = (ptr[rows][:, None] + j)[mask]
            r, c = np.nonzero(mask)
            a_mat[r, c] = va[pos]
            b_mat[r, c] = vb[pos]
            inv[rows] = offset + np.arange(n_w)
        mats.append((a_mat, b_mat))
        counts.append(n_w)
        offset += n_w
    inv[inv < 0] = offset
    return mats, inv.astype(np.int32), counts


def _group_union(keys: np.ndarray, others: np.ndarray, n_key_tiles: int,
                 n_other_tiles: int, group: int, n_blocks_pad: int,
                 widths: Optional[Sequence[int]] = None):
    """Union-gather grouping: `group` CONSECUTIVE key tiles share one
    gathered union of their blocks' other-tiles.

    Consecutive (cluster-ordered) destination tiles reference heavily
    overlapping source tiles — measured on the clustered Reddit shard,
    grouping 2/4/8 dst tiles dedupes the dense path's F-tile reads to
    0.56x/0.33x/0.22x (docs/PERF_NOTES.md). Here each group's union is
    gathered ONCE and consumed directly by one batched contraction over
    (union slot, in-tile) — the F-traffic per group drops from
    sum(K_d) tiles to U = |union| tiles.

    keys/others: [B] key-tile / other-tile id per dense block (key=dst
    for the forward, key=src for the transpose). Returns
    (classes, inv, counts, widths):
      classes[w] = (a_idx [R_w, group, widths[w]] int32 into the padded
        A tensor (pad -> n_blocks_pad, the zero block),
        t_mat [R_w, widths[w]] int32 other-tile ids (pad ->
        n_other_tiles, the zero tile));
      inv [n_key_tiles] int32 -> r * group + d flat position in the
        class-concatenated [sum R_w, group] output (key tiles whose
        whole group has no dense block -> sum(R_w) * group, the zero
        sentinel row);
      counts[w] = real rows in class w. Groups are bucketed into
      x1.5-ladder U-width classes (same padding bound as the bucket
      kernel's degree ladder)."""
    B = int(keys.shape[0])
    n_groups_max = -(-n_key_tiles // group)
    if B == 0:
        widths = list(widths) if widths is not None else [1]
        classes = [(np.full((0, group, w), n_blocks_pad, np.int32),
                    np.full((0, w), n_other_tiles, np.int32))
                   for w in widths]
        inv = np.zeros(n_key_tiles, np.int32)
        return classes, inv, [0] * len(widths), widths
    gid = keys // group
    order = np.lexsort((others, gid))
    g_o, o_o = gid[order], others[order]
    blk_o = np.arange(B, dtype=np.int64)[order]
    d_o = (keys[order] % group).astype(np.int64)
    ug, gcnt = np.unique(g_o, return_counts=True)
    grow = np.repeat(np.arange(ug.shape[0]), gcnt)  # block -> group row
    # union slot of each block within its group: blocks are sorted by
    # (group, other), so a block starts a new union slot iff its
    # (group, other) differs from the previous block's
    new_flag = np.ones(B, bool)
    new_flag[1:] = (g_o[1:] != g_o[:-1]) | (o_o[1:] != o_o[:-1])
    slot = np.cumsum(new_flag) - 1
    gstart = np.zeros(ug.shape[0], np.int64)
    gstart[1:] = np.cumsum(gcnt)[:-1]
    first = slot[gstart]
    u_idx = slot - first[grow]
    u_of_group = np.add.reduceat(new_flag, gstart).astype(np.int64)

    if widths is None:
        widths = _bucket_widths(int(u_of_group.max(initial=1)))
    widths = list(widths)
    widths_arr = np.asarray(widths, dtype=np.int64)
    max_u = int(u_of_group.max(initial=0))
    if max_u > widths[-1]:
        # an explicitly passed ladder (e.g. reused from a group=1
        # layout) may top out below this device's max union size;
        # extend it rather than dropping blocks
        widths += [w for w in _bucket_widths(max_u) if w > widths[-1]]
        widths_arr = np.asarray(widths, dtype=np.int64)
    wid = np.minimum(np.searchsorted(widths_arr, np.maximum(u_of_group, 1)),
                     len(widths) - 1)

    classes, counts = [], []
    concat_row = np.full(n_groups_max, -1, np.int64)
    offset = 0
    for w_i, w in enumerate(widths):
        gsel = np.nonzero(wid == w_i)[0]
        n_w = int(gsel.shape[0])
        a_idx = np.full((n_w, group, w), n_blocks_pad, np.int32)
        t_mat = np.full((n_w, w), n_other_tiles, np.int32)
        if n_w:
            cls_row = np.full(ug.shape[0], -1, np.int64)
            cls_row[gsel] = np.arange(n_w)
            bsel = cls_row[grow] >= 0
            r = cls_row[grow[bsel]]
            a_idx[r, d_o[bsel], u_idx[bsel]] = blk_o[bsel]
            nf = bsel & new_flag
            t_mat[cls_row[grow[nf]], u_idx[nf]] = o_o[nf]
            concat_row[ug[gsel]] = offset + cls_row[gsel]
        classes.append((a_idx, t_mat))
        counts.append(n_w)
        offset += n_w
    key_tiles = np.arange(n_key_tiles, dtype=np.int64)
    gr = concat_row[key_tiles // group]
    inv = np.where(gr >= 0, gr * group + key_tiles % group,
                   offset * group)
    return classes, inv.astype(np.int32), counts, widths


def estimate_block_coverage(sg, tile: int, n_feat_hint: int,
                            nnz_threshold: Optional[int] = None,
                            byte_budget: Optional[int] = DENSE_A_BYTE_BUDGET,
                            ) -> float:
    """Fraction of real edges lying in (dst-tile, src-tile) blocks dense
    enough for the MXU path (>= `nnz_threshold`, defaulting to
    BlockPlan's read-cost break-even).

    The cheap O(E) structural signal `auto` uses to choose between the
    hybrid block kernel and the pure bucket kernel without paying for a
    full plan build. High coverage means the layout (usually
    cluster-renumbered, partition/halo.py `cluster`) concentrates
    community edges into dense tiles. Counting goes through np.unique
    on the occupied block ids (O(E) memory) — a dense bincount over the
    n_dst_tiles x n_src_tiles id space would be tens of GB at
    10M-node-shard scale.

    `byte_budget` mirrors build_sharded_block_tables' HBM cap: without
    it the estimate counts dense blocks the real plan would spill, and
    `auto` could pick the block kernel at a realized coverage far below
    the threshold. The cap tracks the builder's A encoding: 1-bit
    packing when the graph is simple (no duplicate edges) and
    tile % 8 == 0, else the int8 cap (8x fewer blocks) — the bf16/f32
    ratchets (multiplicity > 127) are rare enough to leave optimistic."""
    thr = nnz_threshold if nnz_threshold is not None else max(
        1, (tile * tile) // max(n_feat_hint, 1))
    n_src_rows = sg.n_max + sg.halo_size
    n_src_tiles = -(-n_src_rows // tile)
    cap = None
    if byte_budget is not None:
        bits = 1 if tile % 8 == 0 else 8
        if bits == 1:
            for r in range(sg.num_parts):
                e = int(sg.edge_count[r])
                key = (sg.edge_dst[r][:e].astype(np.int64) * n_src_rows
                       + sg.edge_src[r][:e].astype(np.int64))
                if np.unique(key).shape[0] < key.shape[0]:
                    bits = 8  # duplicate edges -> builder can't bit-pack
                    break
        cap = budget_block_cap(byte_budget, tile, bits)
    dense = tot = 0
    for r in range(sg.num_parts):
        cov, _, d, t = _part_block_stats(sg, r, tile, n_src_tiles, thr,
                                         max_blocks=cap)
        dense += d
        tot += t
    return dense / max(tot, 1)


def _part_block_stats(sg, r: int, tile: int, n_src_tiles: int, thr: int,
                      max_blocks: Optional[int] = None):
    """(coverage, dense_block_count, dense_edges, real_edges) of one
    device's shard at the given tile/threshold — the single definition
    of the dense/remainder split shared by estimate_block_coverage and
    the multichip projection tool. `max_blocks` keeps only the densest
    blocks, matching BlockPlan's budget cutoff."""
    e = int(sg.edge_count[r])
    src = sg.edge_src[r][:e].astype(np.int64)
    dst = sg.edge_dst[r][:e].astype(np.int64)
    real = dst < sg.n_max
    src, dst = src[real], dst[real]
    _, counts = np.unique((dst // tile) * n_src_tiles + (src // tile),
                          return_counts=True)
    sel = counts >= thr
    if max_blocks is not None and int(sel.sum()) > max_blocks:
        kept = np.sort(counts[sel])[-max_blocks:]
        dense, n_dense = int(kept.sum()), int(kept.shape[0])
    else:
        dense, n_dense = int(counts[sel].sum()), int(sel.sum())
    tot = int(src.shape[0])
    return dense / max(tot, 1), n_dense, dense, tot


class BlockPlan:
    """Host-side hybrid plan for one device's edge list.

    Attributes (all numpy, static shapes):
      a_blocks:    [B, T, S] f32 — dense block values (1.0 per edge);
                   block B-1 is NOT special; a zero block is appended
                   on device as index B.
      fwd_groups/fwd_ginv/fwd_gcounts: destination tiles' (A-block,
                   source-tile) pair lists, K-bucketed into power-of-2
                   width classes (_group_by_key) so per-tile padding
                   never exceeds 2x; fwd_ginv restores tile order from
                   the class concatenation.
      bwd_groups/bwd_ginv/bwd_gcounts: the transpose — per source tile,
                   the A-block and destination-tile pairs.
      rem_*:       remainder edges' bucket tables (fwd + transpose).
    """

    def __init__(self, edge_src: np.ndarray, edge_dst: np.ndarray,
                 n_out: int, n_src_rows: int, n_feat: int,
                 tile: int = 256,
                 nnz_threshold: Optional[int] = None,
                 fwd_widths: Optional[Sequence[int]] = None,
                 bwd_widths: Optional[Sequence[int]] = None,
                 fwd_k_widths: Optional[Sequence[int]] = None,
                 bwd_k_widths: Optional[Sequence[int]] = None,
                 max_blocks: Optional[int] = None,
                 group: int = 1):
        T = S = tile
        self.tile = tile
        self.group = max(1, int(group))
        real = edge_dst < n_out
        src = edge_src[real].astype(np.int64)
        dst = edge_dst[real].astype(np.int64)
        n_dst_tiles = -(-n_out // T)
        n_src_tiles = -(-n_src_rows // S)
        self.n_out = n_out
        self.n_src_rows = n_src_rows
        self.n_dst_tiles = n_dst_tiles
        self.n_src_tiles = n_src_tiles

        if nnz_threshold is None:
            # dense block pays T*S A-reads + S*F tile-read amortized;
            # each replaced edge saves an F-wide gather
            nnz_threshold = max(1, (T * S) // max(n_feat, 1))
        bid = (dst // T) * n_src_tiles + (src // S)
        from ..native import stable_argsort

        order = stable_argsort(bid)
        src_o, dst_o, bid_o = src[order], dst[order], bid[order]
        uniq, starts, counts = np.unique(bid_o, return_index=True,
                                         return_counts=True)
        dense_sel = counts >= nnz_threshold
        if max_blocks is not None and int(dense_sel.sum()) > max_blocks:
            # HBM budget: keep only the densest blocks (best edges-
            # replaced-per-byte); the rest spill to the sparse remainder
            cutoff = np.sort(counts[dense_sel])[-max_blocks]
            dense_sel &= counts >= cutoff
            if int(dense_sel.sum()) > max_blocks:  # ties at the cutoff
                over = int(dense_sel.sum()) - max_blocks
                tie_idx = np.nonzero(dense_sel & (counts == cutoff))[0]
                dense_sel[tie_idx[:over]] = False

        # ---- dense blocks ----
        dense_ids = uniq[dense_sel]
        B = int(dense_ids.shape[0])
        # vectorized scatter-add over all dense-block edges (a per-block
        # Python loop is minutes at 100M-edge scale), chunked over block
        # ranges so the int64 bincount transient stays ~2 GB instead of
        # B*T*S*8 bytes (17 GB at Reddit scale)
        in_dense_o = dense_sel[np.searchsorted(uniq, bid_o)]
        k_of_edge = np.searchsorted(dense_ids, bid_o[in_dense_o])
        src_d = src_o[in_dense_o] % S
        dst_d = dst_o[in_dense_o] % T
        self.a_blocks = np.zeros((B, T, S), np.float32)
        blk_chunk = max(1, (1 << 28) // (T * S))  # ~2 GB int64 transient
        # k_of_edge is ascending (edges sorted by bid) -> one searchsorted
        # split per chunk boundary instead of boolean masks
        bounds = np.searchsorted(
            k_of_edge, np.arange(0, B + blk_chunk, blk_chunk))
        for ci in range(len(bounds) - 1):
            lo, hi = bounds[ci], bounds[ci + 1]
            if lo == hi:
                continue
            k0 = ci * blk_chunk
            n_blk = min(blk_chunk, B - k0)
            flat = ((k_of_edge[lo:hi] - k0) * (T * S)
                    + dst_d[lo:hi] * S + src_d[lo:hi])
            self.a_blocks[k0:k0 + n_blk] += np.bincount(
                flat, minlength=n_blk * T * S
            ).astype(np.float32).reshape(n_blk, T, S)
        bd = (dense_ids // n_src_tiles).astype(np.int64)
        bs = (dense_ids % n_src_tiles).astype(np.int64)

        blk_idx = np.arange(B, dtype=np.int64)
        if self.group > 1:
            # union-gather layout: `group` consecutive key tiles share
            # one gathered union of other-tiles (see _group_union)
            (self.fwd_u_classes, self.fwd_u_inv, self.fwd_u_counts,
             self.fwd_k_widths) = _group_union(
                bd, bs, n_dst_tiles, n_src_tiles, self.group, B,
                widths=fwd_k_widths)
            (self.bwd_u_classes, self.bwd_u_inv, self.bwd_u_counts,
             self.bwd_k_widths) = _group_union(
                bs, bd, n_src_tiles, n_dst_tiles, self.group, B,
                widths=bwd_k_widths)
        else:
            self.fwd_k_widths = list(
                fwd_k_widths if fwd_k_widths is not None
                else _bucket_widths(_max_group_count(bd, n_dst_tiles)))
            self.bwd_k_widths = list(
                bwd_k_widths if bwd_k_widths is not None
                else _bucket_widths(_max_group_count(bs, n_src_tiles)))
            self.fwd_groups, self.fwd_ginv, self.fwd_gcounts = \
                _group_by_key(bd, blk_idx, bs, n_dst_tiles,
                              self.fwd_k_widths, pad_a=B,
                              pad_b=n_src_tiles)
            self.bwd_groups, self.bwd_ginv, self.bwd_gcounts = \
                _group_by_key(bs, blk_idx, bd, n_src_tiles,
                              self.bwd_k_widths, pad_a=B,
                              pad_b=n_dst_tiles)

        # ---- sparse remainder (bucket tables both directions) ----
        r_src, r_dst = src_o[~in_dense_o], dst_o[~in_dense_o]
        self.rem_count = int(r_src.shape[0])
        max_in = int(np.bincount(r_dst, minlength=n_out).max(initial=1))
        max_out = int(np.bincount(r_src, minlength=n_src_rows).max(
            initial=1))
        self.rem_fwd_widths = list(
            fwd_widths if fwd_widths is not None
            else _bucket_widths(max(max_in, 1)))
        self.rem_bwd_widths = list(
            bwd_widths if bwd_widths is not None
            else _bucket_widths(max(max_out, 1)))
        self.rem_fwd_mats, self.rem_fwd_inv, self.rem_fwd_counts = \
            build_tables_for_edges(r_src, r_dst, n_out, n_src_rows,
                                   self.rem_fwd_widths)
        self.rem_bwd_mats, self.rem_bwd_inv, self.rem_bwd_counts = \
            build_tables_for_edges(r_dst, r_src, n_src_rows, n_out,
                                   self.rem_bwd_widths)


# bound on one dense-apply chunk's materialized A elements (unpacked,
# compute dtype): 32M elems = 64 MB bf16
_DENSE_CHUNK_ELEMS = 32 * 1024 * 1024


def _apply_classes(classes, compute, per_row_elems, pads, inv, out_tile,
                   n_feat, out_rows):
    """Shared scaffold of the dense applies (per-tile and grouped): run
    `compute` over each class's index mats — chunked via a lax.scan
    over padded row blocks whenever the per-chunk transient would
    exceed _DENSE_CHUNK_ELEMS — then concatenate every class's output
    tiles (plus one zero sentinel row), restore output-tile order with
    `inv`, and flatten tiles to rows.

    classes: list of index-mat tuples (leading axis = class rows);
    compute(*mats) -> [rows, ..., out_tile, n_feat] f32 (extra middle
    axes are flattened into the tile axis); per_row_elems(mats) ->
    transient elements per row (the chunk divisor); pads: per-mat pad
    constants for the scan's padded tail (must point at zero
    blocks/tiles so pad rows compute zeros that get sliced away)."""
    outs = []
    for mats in classes:
        n_w = mats[0].shape[0]
        if n_w == 0:
            continue
        rpc = max(1, _DENSE_CHUNK_ELEMS // max(1, per_row_elems(mats)))
        if n_w <= rpc:
            out = compute(*mats)
        else:
            n_chunks = -(-n_w // rpc)
            pad_rows = n_chunks * rpc - n_w
            padded = tuple(
                jnp.pad(m, ((0, pad_rows),) + ((0, 0),) * (m.ndim - 1),
                        constant_values=p)
                for m, p in zip(mats, pads))

            def body(_, idx):
                return None, compute(*idx)

            _, chunks = jax.lax.scan(
                body, None,
                tuple(m.reshape((n_chunks, rpc) + m.shape[1:])
                      for m in padded))
            out = chunks.reshape((n_chunks * rpc,)
                                 + chunks.shape[2:])[:n_w]
        outs.append(out.reshape(-1, out_tile, n_feat))
    outs.append(jnp.zeros((1, out_tile, n_feat), jnp.float32))
    # mode='clip': indices in-bounds by construction (appended zero
    # rows are the sentinels) — fill-mode gathers are the one path
    # that can mint NaN from valid data (bucket_spmm rationale)
    res = jnp.take(jnp.concatenate(outs, axis=0), inv, axis=0,
                   mode="clip")
    return res.reshape(-1, n_feat)[:out_rows]


def _dense_apply(a_pad, groups, ginv, tiles, T, out_rows, n_feat,
                 compute_dtype, transpose=False, packed=False):
    """For every output tile i: sum_k A[blk(i,k)] (@ or transposed-@)
    tiles[tile(i,k)], where the (blk, tile) pair lists are K-bucketed
    into power-of-2 width classes (`groups`: [(blk_mat, tile_mat)] per
    class, `ginv` restoring tile order — see _group_by_key).

    a_pad: [B+1, T, S] in its STORED dtype (possibly int8; last block =
    zeros) — or, with packed=True, bit-packed [B+1, T, S//8] uint8 —
    the cast/unpack to the compute dtype happens per chunk on the
    gathered [R, K, T, S] slice, so the full A tensor is never
    materialized in a wider dtype; likewise the backward's A^T lives in
    the einsum spec, never as a transposed copy. tiles: [n_tiles+1, S,
    F] (last = zeros). Returns [n_out_tiles*T, F] f32.

    Each class runs as one batched contraction ([R, T, K*S] @
    [R, K*S, F] after XLA canonicalization — MXU-shaped), chunked over
    rows so the unpacked A transient stays bounded (_apply_classes)."""
    spec = "rkts,rktf->rsf" if transpose else "rkts,rksf->rtf"
    s = a_pad.shape[-1] * 8 if packed else a_pad.shape[-1]

    def compute(bi, ti):  # [R, K] x2 -> [R, T, F] f32
        blks = jnp.take(a_pad, bi, axis=0, mode="clip")
        blks = _unpack_bits(blks, s, compute_dtype) if packed \
            else blks.astype(compute_dtype)
        tls = jnp.take(tiles, ti, axis=0,
                       mode="clip")           # [R, K, S|T, F]
        return jnp.einsum(spec, blks, tls,
                          preferred_element_type=jnp.float32)

    # transients: unpacked A [R, K, T, S] + gathered tiles [R, K, S, F]
    return _apply_classes(
        groups, compute,
        lambda mats: mats[0].shape[1] * s * max(T, n_feat),
        (a_pad.shape[0] - 1, tiles.shape[0] - 1),
        ginv, T, n_feat, out_rows)


def _dense_apply_grouped(a_pad, classes, inv, tiles, T, out_rows,
                         n_feat, compute_dtype, transpose=False,
                         packed=False):
    """Union-gather dense apply: for every group of `group` consecutive
    output tiles, gather the union of the group's source tiles ONCE
    ([R, U, S, F]) and consume it directly in one batched contraction
    against the group's gathered A blocks ([R, group, U, T, S]) — the
    per-tile F-traffic dedupe _group_union documents.

    classes: [(a_idx [R, group, U_w], t_mat [R, U_w])] per U-width
    class; inv restores output-tile order from the class-concatenated
    [sum R_w * group] flat tile axis. Forward contracts (u, s) -> out
    [R, group, T, F]; transpose contracts (u, t) -> [R, group, S, F]
    (the backward's per-source-tile sum of A^T @ g)."""
    spec = "rduts,rutf->rdsf" if transpose else "rduts,rusf->rdtf"
    s = a_pad.shape[-1] * 8 if packed else a_pad.shape[-1]

    def compute(ai, ti):  # [R, group, U] + [R, U] -> [R, group, T|S, F]
        blks = jnp.take(a_pad, ai, axis=0,
                        mode="clip")          # [R, G, U, T, S(/8)]
        blks = _unpack_bits(blks, s, compute_dtype) if packed \
            else blks.astype(compute_dtype)
        tls = jnp.take(tiles, ti, axis=0,
                       mode="clip")           # [R, U, S|T, F]
        return jnp.einsum(spec, blks, tls,
                          preferred_element_type=jnp.float32)

    # transients: unpacked A [R, G, U, T, S] + gathered union tiles
    # [R, U, S, F] (F can exceed G*T on wide input layers); square
    # tiles, so the output's in-tile dim is T in both directions
    return _apply_classes(
        classes, compute,
        lambda mats: max(mats[0].shape[1] * mats[0].shape[2] * T * s,
                         mats[0].shape[2] * s * n_feat),
        (a_pad.shape[0] - 1, tiles.shape[0] - 1),
        inv, T, n_feat, out_rows)


def make_block_spmm_fn(
    plan_arrays: Dict[str, jax.Array],
    in_deg: jax.Array,
    n_out: int,
    n_src_rows: int,
    tile: int,
    chunk_edges: Optional[int] = None,
    rem_dtype: Optional[str] = None,
    rem_amax: bool = False,
):
    """Differentiable hybrid mean-aggregation closure f(fbuf [R, F]) ->
    f32 [n_out, F]. `plan_arrays` holds the BlockPlan tensors (see
    sharded_block_tables for keys), already stripped to per-device blocks
    when used inside shard_map. `rem_dtype` narrows the REMAINDER's
    gather transport only (bucket_spmm.transport_dtypes) — the dense
    MXU path keeps the activation dtype. `rem_amax` swaps the static
    saturating fp8 cast for the amax-clamped one (the de-scale applies
    to the remainder alone, before it joins the dense partial)."""
    from .bucket_spmm import (amax_transport_cast, transport_cast,
                              transport_dtypes)

    d = plan_arrays
    deg_col = in_deg[:, None]
    T = tile
    rem_fwd_dt, rem_bwd_dt = transport_dtypes(rem_dtype)

    def _rem_cast(x, dt):
        if rem_amax:
            return amax_transport_cast(x, dt)
        return transport_cast(x, dt), None

    def tiles_of(x, n_tiles, S):
        rpad = n_tiles * S - x.shape[0]
        xp = jnp.pad(x, ((0, rpad + S), (0, 0)))  # + one zero tile
        return xp.reshape(n_tiles + 1, S, x.shape[-1])

    def rem_mats(prefix):
        return [d[k] for k in sorted(d)
                if k.startswith(prefix) and not k.endswith("inv")]

    def dense_groups(direction):  # [(blk_mat, tile_mat)] in width order
        bs_ = sorted(k[:-1] for k in d
                     if k.startswith(f"blk_{direction}_g")
                     and k.endswith("b"))
        return [(d[k + "b"], d[k + "t"]) for k in bs_]

    def union_classes(direction):  # [(a_idx, t_mat)] in U-width order
        bs_ = sorted(k[:-1] for k in d
                     if k.startswith(f"blk_{direction}u_g")
                     and k.endswith("a"))
        return [(d[k + "a"], d[k + "t"]) for k in bs_]

    grouped = "blk_fwdu_inv" in d
    packed = "blk_a_bits" in d

    def a_padded():
        # append the zero block IN the stored dtype (bit-packed uint8 /
        # int8/bf16/f32); the per-step unpack/cast to the compute dtype
        # lives in _dense_apply
        a = d["blk_a_bits"] if packed else d["blk_a"]
        return jnp.concatenate(
            [a, jnp.zeros((1,) + a.shape[1:], a.dtype)], axis=0)

    @jax.custom_vjp
    def f(fbuf):
        n_s_tiles = -(-n_src_rows // T)
        tiles = tiles_of(fbuf, n_s_tiles, T)
        if grouped:
            dense = _dense_apply_grouped(
                a_padded(), union_classes("fwd"), d["blk_fwdu_inv"],
                tiles, T, n_out, fbuf.shape[-1], fbuf.dtype,
                packed=packed)
        else:
            dense = _dense_apply(a_padded(), dense_groups("fwd"),
                                 d["blk_fwd_ginv"], tiles, T, n_out,
                                 fbuf.shape[-1], fbuf.dtype,
                                 packed=packed)
        rem_in, rem_inv = _rem_cast(fbuf, rem_fwd_dt)
        rem = bucket_aggregate(
            rem_in, rem_mats("blkrem_fwd_"), d["blkrem_fwd_inv"],
            chunk_edges=chunk_edges,
            run_plans=extract_run_plans(d, "blkrem_fwd"))
        if rem_inv is not None:
            rem = rem * rem_inv
        return (dense + rem) / deg_col

    def fwd(fbuf):
        return f(fbuf), jnp.zeros((0,), fbuf.dtype)

    def bwd(proto, g):
        gd32 = g.astype(jnp.float32) / deg_col
        gd = gd32.astype(proto.dtype)
        # transpose dense: per source tile, sum A^T @ g_tile
        n_d_tiles = -(-n_out // T)
        g_tiles = tiles_of(gd, n_d_tiles, T)
        if grouped:
            dense = _dense_apply_grouped(
                a_padded(), union_classes("bwd"), d["blk_bwdu_inv"],
                g_tiles, T, n_src_rows, g.shape[-1], gd.dtype,
                transpose=True, packed=packed)
        else:
            dense = _dense_apply(a_padded(), dense_groups("bwd"),
                                 d["blk_bwd_ginv"], g_tiles, T,
                                 n_src_rows, g.shape[-1], gd.dtype,
                                 transpose=True, packed=packed)
        # the remainder's transport cast comes straight from the f32
        # cotangent — not through the proto.dtype rounding above
        # (matching bucket_spmm's single-rounding path)
        if rem_bwd_dt is not None:
            rem_in, rem_inv = _rem_cast(gd32, rem_bwd_dt)
        else:
            rem_in, rem_inv = gd, None
        rem = bucket_aggregate(
            rem_in, rem_mats("blkrem_bwd_"), d["blkrem_bwd_inv"],
            chunk_edges=chunk_edges,
            run_plans=extract_run_plans(d, "blkrem_bwd"))
        if rem_inv is not None:
            rem = rem * rem_inv
        return ((dense + rem).astype(proto.dtype),)

    f.defvjp(fwd, bwd)
    return f


def plan_to_arrays(p: BlockPlan) -> Dict[str, np.ndarray]:
    """Flatten a BlockPlan into the array dict make_block_spmm_fn uses."""
    arrs = {
        "blk_a": p.a_blocks,
        "blkrem_fwd_inv": p.rem_fwd_inv,
        "blkrem_bwd_inv": p.rem_bwd_inv,
    }
    if p.group > 1:
        arrs["blk_fwdu_inv"] = p.fwd_u_inv
        arrs["blk_bwdu_inv"] = p.bwd_u_inv
        for direction, classes in (("fwd", p.fwd_u_classes),
                                   ("bwd", p.bwd_u_classes)):
            for w_i, (a_idx, t_mat) in enumerate(classes):
                if a_idx.shape[0]:
                    arrs[f"blk_{direction}u_g{w_i:02d}a"] = a_idx
                    arrs[f"blk_{direction}u_g{w_i:02d}t"] = t_mat
    else:
        arrs["blk_fwd_ginv"] = p.fwd_ginv
        arrs["blk_bwd_ginv"] = p.bwd_ginv
        for direction, groups in (("fwd", p.fwd_groups),
                                  ("bwd", p.bwd_groups)):
            for w_i, (a_mat, b_mat) in enumerate(groups):
                if a_mat.shape[0]:
                    arrs[f"blk_{direction}_g{w_i:02d}b"] = a_mat
                    arrs[f"blk_{direction}_g{w_i:02d}t"] = b_mat
    for b, m in enumerate(p.rem_fwd_mats):
        if m.shape[0]:
            arrs[f"blkrem_fwd_{b:02d}"] = m
    for b, m in enumerate(p.rem_bwd_mats):
        if m.shape[0]:
            arrs[f"blkrem_bwd_{b:02d}"] = m
    return arrs


def build_sharded_block_tables(sg, tile: int = 256,
                               n_feat_hint: int = 256,
                               byte_budget: int = DENSE_A_BYTE_BUDGET,
                               nnz_threshold: Optional[int] = None,
                               group: int = 1,
                               slab: bool = False,
                               ) -> Tuple[Dict[str, np.ndarray], int]:
    """Stacked per-device hybrid plans (leading device axis), padded to
    shared shapes: same B (dense block count), same K (per-tile block
    list width), same remainder bucket ladders/caps. `slab` emits
    streaming-slab plans for the remainder tables (bucket_spmm
    add_slab_plans). Returns (tables, tile)."""
    P = sg.num_parts
    n_src_rows = sg.n_max + sg.halo_size
    # HBM budget for the per-device dense-A tensor: keep the densest
    # blocks under byte_budget, spill the rest to the sparse remainder.
    # Past this size the A reads stop paying for the gathers they
    # replace and, at Reddit scale, the table alone would crowd a v5e's
    # 16 GB HBM (an unbudgeted clustered Reddit shard produced 6.5 GB).
    # First pass assumes bit-packed A (1 bit per entry — the common
    # case: simple graphs have 0/1 edge multiplicities); if the counts
    # force a wider dtype, plans rebuild under the correspondingly
    # smaller cap.
    def cap_for(bits: int) -> int:
        return budget_block_cap(byte_budget, tile, bits)

    # narrowest exact encoding for the A counts: 1-bit packing (counts
    # <= 1) buys 8x the dense coverage of int8 (<= 127) per HBM byte,
    # which in turn halves bf16 and quarters f32 (the device
    # unpacks/casts A to the activation dtype at use)
    import ml_dtypes

    def build_plans(cap, fw=None, bw=None, fk=None, bk=None):
        # fresh ladders unless given: a different block cap changes
        # which edges land in the remainder, and reusing a ladder built
        # for a different remainder can under-size its top bucket —
        # build_tables_for_edges would then SILENTLY drop edges
        return [
            BlockPlan(sg.edge_src[r], sg.edge_dst[r], sg.n_max,
                      n_src_rows, n_feat_hint, tile=tile,
                      nnz_threshold=nnz_threshold,
                      fwd_widths=fw, bwd_widths=bw,
                      fwd_k_widths=fk, bwd_k_widths=bk, max_blocks=cap,
                      group=group)
            for r in range(P)
        ]

    def required_bits(plans):
        a_max = max((float(p.a_blocks.max(initial=0.0)) for p in plans),
                    default=0.0)
        if a_max <= 1 and tile % 8 == 0:  # pack_a_blocks needs S % 8
            return 1, None  # bit-packed uint8 (pack_a_blocks)
        if a_max <= 127:
            return 8, np.int8
        if a_max <= 256:
            return 16, ml_dtypes.bfloat16
        return 32, np.float32

    # fixpoint on the A encoding: cap = budget / (bits per entry), but
    # the counts (and thus the bits required for exactness) depend on
    # which blocks the cap keeps. bits only ratchets up, so this
    # terminates in <= 4 builds. The SHIPPED encoding (emit_bits /
    # a_dtype) is re-read off the final plans: it may be narrower than
    # the cap assumed (e.g. the smaller cap dropped every multi-edge
    # block) — exact, merely under-using the budget.
    bits = 1
    while True:
        plans = build_plans(cap_for(bits))
        emit_bits, a_dtype = required_bits(plans)
        if emit_bits <= bits:
            break
        bits = emit_bits

    # unify ladders (length = max over devices): remainder bucket widths
    # AND dense K-class widths. The re-build keeps the SAME cap, so the
    # dense selection — and thus every remainder degree and per-tile
    # block count — is unchanged and the unified ladders (covering the
    # global max) are safe for every device
    fw_len = max(len(p.rem_fwd_widths) for p in plans)
    bw_len = max(len(p.rem_bwd_widths) for p in plans)
    fk_len = max(len(p.fwd_k_widths) for p in plans)
    bk_len = max(len(p.bwd_k_widths) for p in plans)
    fw = ladder_prefix(fw_len)
    bw = ladder_prefix(bw_len)
    fk = ladder_prefix(fk_len)
    bk = ladder_prefix(bk_len)
    if any(p.rem_fwd_widths != fw or p.rem_bwd_widths != bw
           or p.fwd_k_widths != fk or p.bwd_k_widths != bk
           for p in plans):
        plans = build_plans(cap_for(bits), fw=fw, bw=bw, fk=fk, bk=bk)

    B_max = max(p.a_blocks.shape[0] for p in plans)
    fwd_caps = [max(p.rem_fwd_counts[b] for p in plans)
                for b in range(fw_len)]
    bwd_caps = [max(p.rem_bwd_counts[b] for p in plans)
                for b in range(bw_len)]

    def dense_counts(p, direction):
        if group > 1:
            return (p.fwd_u_counts if direction == "fwd"
                    else p.bwd_u_counts)
        return p.fwd_gcounts if direction == "fwd" else p.bwd_gcounts

    fk_caps = [max(dense_counts(p, "fwd")[w] for p in plans)
               for w in range(fk_len)]
    bk_caps = [max(dense_counts(p, "bwd")[w] for p in plans)
               for w in range(bk_len)]

    def reoffset_inv(inv, counts, caps):
        inv = inv.astype(np.int64)
        out = np.full_like(inv, sum(caps))
        off_old = off_new = 0
        for n_b, cap in zip(counts, caps):
            sel = (inv >= off_old) & (inv < off_old + n_b)
            out[sel] = inv[sel] - off_old + off_new
            off_old += n_b
            off_new += cap
        return out.astype(np.int32)

    tables: Dict[str, List[np.ndarray]] = {}
    for p in plans:
        B = p.a_blocks.shape[0]
        a_pad = _pad_rows(p.a_blocks, B_max, 0.0)
        arrs = {
            # pad dense blocks to B_max with zero blocks; pad indices
            # point at the appended zero block (index B_max on device)
            ("blk_a_bits" if emit_bits == 1 else "blk_a"):
                pack_a_blocks(a_pad) if emit_bits == 1
                else a_pad.astype(a_dtype),
            "blkrem_fwd_inv": reoffset_inv(p.rem_fwd_inv,
                                           p.rem_fwd_counts, fwd_caps),
            "blkrem_bwd_inv": reoffset_inv(p.rem_bwd_inv,
                                           p.rem_bwd_counts, bwd_caps),
        }
        if group > 1:
            # inv entries encode r * group + d; reoffset the row part
            # to the shared per-class caps (sentinel sum(counts)*G ->
            # sum(caps)*G falls out of reoffset_inv's default)
            arrs["blk_fwdu_inv"] = (
                reoffset_inv(p.fwd_u_inv // group, p.fwd_u_counts,
                             fk_caps).astype(np.int64) * group
                + p.fwd_u_inv % group).astype(np.int32)
            arrs["blk_bwdu_inv"] = (
                reoffset_inv(p.bwd_u_inv // group, p.bwd_u_counts,
                             bk_caps).astype(np.int64) * group
                + p.bwd_u_inv % group).astype(np.int32)
            for direction, classes, caps in (
                    ("fwd", p.fwd_u_classes, fk_caps),
                    ("bwd", p.bwd_u_classes, bk_caps)):
                for w_i, (a_idx, t_mat) in enumerate(classes):
                    if not caps[w_i]:
                        continue
                    a_idx = np.where(a_idx == B, B_max, a_idx)
                    arrs[f"blk_{direction}u_g{w_i:02d}a"] = _pad_rows(
                        a_idx, caps[w_i], B_max).astype(np.int32)
                    arrs[f"blk_{direction}u_g{w_i:02d}t"] = _pad_rows(
                        t_mat, caps[w_i],
                        p.n_src_tiles if direction == "fwd"
                        else p.n_dst_tiles).astype(np.int32)
        else:
            arrs["blk_fwd_ginv"] = reoffset_inv(p.fwd_ginv,
                                                p.fwd_gcounts, fk_caps)
            arrs["blk_bwd_ginv"] = reoffset_inv(p.bwd_ginv,
                                                p.bwd_gcounts, bk_caps)
            for direction, groups, caps in (
                    ("fwd", p.fwd_groups, fk_caps),
                    ("bwd", p.bwd_groups, bk_caps)):
                for w_i, (a_mat, b_mat) in enumerate(groups):
                    if not caps[w_i]:
                        continue
                    # remap this device's pad-block id B to the shared
                    # zero block B_max; pad rows point at it entirely
                    # (the matching tile pad is the zero tile, already
                    # shared)
                    a_mat = np.where(a_mat == B, B_max, a_mat)
                    arrs[f"blk_{direction}_g{w_i:02d}b"] = _pad_rows(
                        a_mat, caps[w_i], B_max).astype(np.int32)
                    arrs[f"blk_{direction}_g{w_i:02d}t"] = _pad_rows(
                        b_mat, caps[w_i],
                        p.n_src_tiles if direction == "fwd"
                        else p.n_dst_tiles).astype(np.int32)
        for b in range(fw_len):
            if fwd_caps[b]:
                arrs[f"blkrem_fwd_{b:02d}"] = _pad_rows(
                    p.rem_fwd_mats[b], fwd_caps[b], n_src_rows)
        for b in range(bw_len):
            if bwd_caps[b]:
                arrs[f"blkrem_bwd_{b:02d}"] = _pad_rows(
                    p.rem_bwd_mats[b], bwd_caps[b], sg.n_max)
        for k, v in arrs.items():
            tables.setdefault(k, []).append(v)
    stacked = {k: np.stack(v) for k, v in tables.items()}
    if slab:
        add_slab_plans(stacked, ("blkrem_fwd", n_src_rows),
                       ("blkrem_bwd", sg.n_max))
    return stacked, tile


def make_device_block_spmm_fn(d: Dict[str, jax.Array], in_deg: jax.Array,
                              n_out: int, n_src_rows: int, tile: int,
                              chunk_edges: Optional[int] = None,
                              rem_dtype: Optional[str] = None,
                              rem_amax: bool = False):
    """Bind per-device blocks of build_sharded_block_tables (inside
    shard_map, leading device axis stripped)."""
    plan_arrays = {k: v for k, v in d.items()
                   if k.startswith(("blk_", "blkrem_"))}
    return make_block_spmm_fn(
        plan_arrays, in_deg, n_out, n_src_rows, tile, chunk_edges,
        rem_dtype, rem_amax)
