"""Pallas CSR SpMM (mean aggregation) for VMEM-resident shards.

The TPU-native replacement for DGL's CUDA SpMM kernel (reference
module/layer.py:47-49) in the regime where it pays off: when a device's
feature buffer fits in VMEM (~16 MB/core). With P partitions over a
large graph, per-shard fbuf shrinks as 1/P, so the many-chip scaling
case — the whole point of PipeGCN — is exactly the regime this kernel
targets. Keeping fbuf on-chip makes the per-edge source-row reads VMEM
loads instead of random HBM traffic; destination rows are produced
row-block by row-block with edges streamed via one DMA per block.

Outside that regime (fbuf larger than the VMEM budget), the XLA
gather + sorted-segment-sum path in ops/spmm.py is the right algorithm
— TPU's hardware gather beats anything a hand-written per-edge DMA loop
can do over HBM — and the trainer's spmm_impl='auto' falls back to it
(parallel/trainer.py _setup_pallas_spmm).

Layout contract (per device, produced by partition.halo.ShardedGraph):
edges sorted by destination (CSR); `row_ptr[i]` = first edge of dst row
i. The kernel grid walks row blocks of 8 destinations; each step DMAs
that block's edge-source indices into a VMEM scratch and accumulates
its 8 output rows with an unrolled per-row edge loop.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# STATUS: experimental (README "TPU-native extensions"). The premise —
# per-shard fbuf fitting VMEM at high P — lacks a measured winning
# regime: halo rows GROW with P on real partitions (an 8-way METIS
# Reddit split carries 2.2-5.5M halo rows/device,
# results/multichip_projection.md), and out-of-budget shards compile
# heavily-spilled programs (one crashed the tunneled TPU worker).
# `auto` only selects this kernel when sharded_applicable() passes;
# bucket/block are the production paths.

ROW_BLOCK = 8           # dst rows per grid step (fp32 sublane tile)
VMEM_BUDGET = 12 << 20  # conservative fbuf budget (bytes) of ~16MB VMEM


def build_row_ptr(edge_dst: np.ndarray, n_out: int) -> np.ndarray:
    """CSR row pointers from dst-sorted edges (padding rows whose dst is
    the sentinel `n_out` fall beyond row_ptr[n_out] and are ignored)."""
    return np.searchsorted(edge_dst, np.arange(n_out + 1)).astype(np.int32)


def _block_tables(row_ptr: np.ndarray, n_out: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Per-row start/end tables padded to the row-block grid, plus the
    max edges any block touches (the edge-scratch/DMA size)."""
    n_blocks = -(-n_out // ROW_BLOCK)
    n_pad = n_blocks * ROW_BLOCK
    starts = np.full(n_pad, row_ptr[-1], dtype=np.int32)
    ends = np.full(n_pad, row_ptr[-1], dtype=np.int32)
    starts[:n_out] = row_ptr[:-1]
    ends[:n_out] = row_ptr[1:]
    # 2-D [n_blocks, ROW_BLOCK] layout: rank-1 SMEM blocks of width
    # ROW_BLOCK fail Mosaic's lowering constraint (block width must be
    # the whole array or a multiple of the 128-wide tiling); a
    # (1, ROW_BLOCK) block over a 2-D table lowers fine
    starts = starts.reshape(n_blocks, ROW_BLOCK)
    ends = ends.reshape(n_blocks, ROW_BLOCK)
    blk_start = starts[:, 0]
    blk_end = ends[:, -1]
    max_e = int((blk_end - blk_start).max()) if n_blocks else 0
    max_e = max(-(-max_e // 128) * 128, 128)
    return starts, ends, max_e


def _kernel(starts_ref, ends_ref, deg_ref, esrc_hbm, fbuf_ref, out_ref,
            eidx, sem, *, max_e, n_feat):
    s0 = starts_ref[0, 0]
    # one DMA brings every edge-source index this block can touch
    cp = pltpu.make_async_copy(esrc_hbm.at[pl.ds(s0, max_e)], eidx, sem)
    cp.start()
    cp.wait()

    def row_body(r):
        lo = starts_ref[0, r] - s0
        hi = ends_ref[0, r] - s0

        def edge_body(k, acc):
            src = eidx[k]
            return acc + fbuf_ref[src, :]

        acc = jax.lax.fori_loop(
            lo, hi, edge_body, jnp.zeros((n_feat,), jnp.float32)
        )
        out_ref[r, :] = acc / deg_ref[0, r]

    for r in range(ROW_BLOCK):  # static unroll over the 8 block rows
        row_body(r)


@functools.partial(
    jax.jit, static_argnames=("n_out", "max_e", "interpret", "vma")
)
def _spmm_pallas_call(fbuf, edge_src_padded, starts, ends, in_deg_padded,
                      n_out, max_e, interpret=False, vma=None):
    n_blocks = starts.shape[0]
    n_feat = fbuf.shape[-1]
    kernel = functools.partial(_kernel, max_e=max_e, n_feat=n_feat)
    out_shape = (n_blocks * ROW_BLOCK, n_feat)
    if vma is not None:
        # inside shard_map with check_vma the output's varying mesh axes
        # must be declared explicitly (older jax: compat drops the kwarg)
        from ..compat import shape_dtype_struct

        out_sds = shape_dtype_struct(out_shape, jnp.float32, vma=vma)
    else:
        out_sds = jax.ShapeDtypeStruct(out_shape, jnp.float32)
    out = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, ROW_BLOCK), lambda b: (b, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, ROW_BLOCK), lambda b: (b, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, ROW_BLOCK), lambda b: (b, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # edge_src in HBM
            pl.BlockSpec(memory_space=pltpu.VMEM),  # fbuf resident
        ],
        out_specs=pl.BlockSpec((ROW_BLOCK, n_feat), lambda b: (b, 0)),
        out_shape=out_sds,
        scratch_shapes=[
            pltpu.VMEM((max_e,), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(starts, ends, in_deg_padded, edge_src_padded, fbuf)
    return out[:n_out]


class PallasSpmm:
    """Host-side plan + callable for one shard's CSR layout.

    Precomputes the block tables once (they depend only on the graph);
    `__call__(fbuf)` then runs the kernel. `applicable` is False when
    fbuf exceeds the VMEM budget or the edge scratch would be outsized
    (extreme hub blocks) — callers should fall back to ops.spmm then.
    """

    def __init__(self, edge_src: np.ndarray, edge_dst: np.ndarray,
                 in_deg: np.ndarray, n_out: int, n_src_rows: int,
                 n_feat: int, interpret: bool = False):
        row_ptr = build_row_ptr(np.asarray(edge_dst), n_out)
        starts, ends, max_e = _block_tables(row_ptr, n_out)
        self.n_out = n_out
        self.max_e = max_e
        self.interpret = interpret
        n_pad = starts.size
        # pad the edge array so the fixed-size DMA never over-reads
        esrc = np.asarray(edge_src, dtype=np.int32)
        self._esrc = jnp.asarray(
            np.concatenate([esrc, np.zeros(max_e, np.int32)])
        )
        self._starts = jnp.asarray(starts)
        self._ends = jnp.asarray(ends)
        deg = np.ones(n_pad, np.float32)
        deg[:n_out] = np.asarray(in_deg, np.float32)[:n_out]
        self._deg = jnp.asarray(deg.reshape(starts.shape))
        self.applicable = sharded_applicable(n_src_rows, n_feat, max_e)

    def __call__(self, fbuf: jax.Array) -> jax.Array:
        return _spmm_pallas_call(
            fbuf, self._esrc, self._starts, self._ends, self._deg,
            self.n_out, self.max_e, self.interpret,
        )


def build_sharded_tables(sg) -> Tuple[dict, int, int]:
    """Stacked per-device kernel tables for use inside shard_map.

    Returns ({'spmm_starts','spmm_ends','spmm_esrc','spmm_deg'} each with
    leading device axis, global max_e, max fbuf rows). Tables differ per
    device, so they ship as sharded step inputs rather than plan-object
    closures. max_e is the global maximum so the traced program is
    identical on every device.
    """
    P = sg.num_parts
    n_src_rows = sg.n_max + sg.halo_size
    all_starts, all_ends, max_e = [], [], 128
    t_gather = np.zeros_like(sg.edge_dst, dtype=np.int32)
    t_scatter = np.zeros_like(sg.edge_src, dtype=np.int32)
    for r in range(P):
        row_ptr = build_row_ptr(np.asarray(sg.edge_dst[r]), sg.n_max)
        s, e, me = _block_tables(row_ptr, sg.n_max)
        all_starts.append(s)
        all_ends.append(e)
        max_e = max(max_e, me)
        # transpose tables for the backward pass: gradient flows dst->src
        # (gather rows of the cotangent by dst, scatter-add into source
        # rows); pad edges (dst == sentinel n_max) must scatter into the
        # dropped segment n_src_rows, not into node 0
        src_r = np.asarray(sg.edge_src[r], dtype=np.int64)
        dst_r = np.asarray(sg.edge_dst[r], dtype=np.int64)
        is_pad = dst_r == sg.n_max
        scat = np.where(is_pad, n_src_rows, src_r)
        gath = np.where(is_pad, 0, dst_r)
        from ..native import stable_argsort

        order = stable_argsort(scat)
        t_gather[r] = gath[order].astype(np.int32)
        t_scatter[r] = scat[order].astype(np.int32)
    blk_shape = all_starts[0].shape
    esrc = np.concatenate(
        [sg.edge_src.astype(np.int32),
         np.zeros((P, max_e), np.int32)], axis=1,
    )
    deg = np.ones((P, blk_shape[0] * blk_shape[1]), np.float32)
    deg[:, : sg.n_max] = sg.in_deg
    deg = deg.reshape((P,) + blk_shape)
    tables = {
        "spmm_starts": np.stack(all_starts),
        "spmm_ends": np.stack(all_ends),
        "spmm_esrc": esrc,
        "spmm_deg": deg,
        "spmm_t_gather": t_gather,
        "spmm_t_scatter": t_scatter,
    }
    return tables, max_e, n_src_rows


def make_device_spmm_fn(d: dict, n_max: int, n_src_rows: int, max_e: int,
                        interpret: bool, chunk: Optional[int] = None,
                        axis_name: str = "parts"):
    """Differentiable per-device mean-SpMM closure over sharded tables
    (call inside shard_map). Forward = the Pallas kernel; backward = the
    transpose aggregation via the XLA sorted-segment path."""
    from .spmm import spmm_sum

    deg_col = d["spmm_deg"].reshape(-1)[:n_max][:, None]
    vma = frozenset((axis_name,))

    @jax.custom_vjp
    def f(fbuf):
        return _spmm_pallas_call(
            fbuf, d["spmm_esrc"], d["spmm_starts"], d["spmm_ends"],
            d["spmm_deg"], n_max, max_e, interpret, vma,
        )

    def fwd(fbuf):
        # zero-size proto carries fbuf's dtype (residuals must be JAX types)
        return f(fbuf), jnp.zeros((0,), fbuf.dtype)

    def bwd(proto, g):
        gd = g / deg_col
        # transpose aggregation accumulates in f32 (spmm_sum converts);
        # cast the cotangent back to the activation dtype once
        d_fbuf = spmm_sum(gd, d["spmm_t_gather"], d["spmm_t_scatter"],
                          n_src_rows, chunk, sorted_edges=True)
        return (d_fbuf.astype(proto.dtype),)

    f.defvjp(fwd, bwd)
    return f


def sharded_applicable(n_src_rows: int, n_feat_max: int, max_e: int) -> bool:
    return (n_src_rows * n_feat_max * 4 <= VMEM_BUDGET
            and max_e * 4 <= (2 << 20))


def sharded_fits(sg, width: int) -> bool:
    """Full applicability check for a sharded graph at feature width
    `width`: the cheap shape-only gate first, then — only when shapes
    alone cannot reject — the O(E) table build to check max_e. Large
    shards (where the build would be an expensive multi-GB transient)
    always fail the shape gate, so the build only runs when it is
    cheap."""
    n_src_rows = sg.n_max + sg.halo_size
    if not sharded_applicable(n_src_rows, width, 0):
        return False
    _, max_e, n_src_rows = build_sharded_tables(sg)
    return sharded_applicable(n_src_rows, width, max_e)
