"""Fused unpack+matmul Pallas kernel for the union-gather dense path.

The production block kernel (ops/block_spmm.py) covers ~80% of edges
with bit-packed dense tiles contracted on the MXU — the TPU-native
replacement for the reference's DGL SpMM (reference module/layer.py:
47-49). Its XLA formulation pays two HBM transients per contraction
that this kernel eliminates (docs/PERF_NOTES.md "fused unpack+matmul"
design note, measured as the ~0.3 s/epoch A/F-collapse deltas of the
--probe-traffic microbench):

  1. the device-side bit-unpack MATERIALIZES the gathered A blocks as
     a [rows, G, U, T, S] bf16 tensor between two HBM round-trips
     (XLA does not fuse elementwise producers into a dot), ~264 KB
     realized per 8 KB packed block;
  2. the gathered F-tile unions ([rows, U, S, F]) round-trip HBM once
     more between the gather and the einsum.

Here both stay in VMEM: the grid walks (row, union-slot); each step's
[S, F] source tile arrives through the scalar-prefetch BlockSpec
pipeline (auto double-buffered by Pallas), the G referenced 8 KB
packed A blocks arrive through manually double-buffered async DMAs,
the bit-unpack runs on the VPU registers-to-registers, and the MXU
accumulates straight into a VMEM-resident [G, T, F] f32 output block.
Per-row HBM traffic drops to exactly the packed bytes + each union
tile once.

Layout contract: A blocks are bit-packed along the SUBLANE (row) axis
— uint8 [B, T//8, S], bit k of packed[b, i, s] = A[b, 8i+k, s],
little-endian — produced by repack_bits_sublane from the stored
lane-packed tables. Sublane packing unpacks with a lane-preserving
repeat + shift, which Mosaic lowers without relayouts (the lane-packed
[T, S//8] layout would put a 32-wide minor axis in VMEM).

STATUS: measured-gate pending (the previous Pallas kernel is demoted
precisely for lacking a winning regime — ops/pallas_spmm.py). Reached
only via --block-fused; `auto` never selects it until a chip
measurement lands.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def repack_bits_sublane(a_bits: np.ndarray,
                        chunk: int = 2048) -> np.ndarray:
    """Lane-packed [B, T, S//8] uint8 (pack_a_blocks) -> sublane-packed
    [B, T//8, S] uint8, chunked so the unpacked bool transient stays
    ~chunk * T * S bytes."""
    B, T, S8 = a_bits.shape
    assert T % 8 == 0, a_bits.shape
    out = np.empty((B, T // 8, S8 * 8), np.uint8)
    for i in range(0, max(B, 1), chunk):
        blk = a_bits[i:i + chunk]
        bits = np.unpackbits(blk, axis=-1, bitorder="little")
        out[i:i + chunk] = np.packbits(
            bits.reshape(blk.shape[0], T // 8, 8, S8 * 8),
            axis=2, bitorder="little")[:, :, 0, :]
    return out


def _unpack_sublane(x: jax.Array, compute_dtype) -> jax.Array:
    """Kernel-side inverse of repack_bits_sublane on one [T//8, S]
    uint8 block -> [T, S] in the compute dtype. repeat(8, axis=0) puts
    packed row t//8 at row t; the shift selects bit t%8."""
    xi = jnp.repeat(x.astype(jnp.int32), 8, axis=0)
    shift = jax.lax.broadcasted_iota(jnp.int32, xi.shape, 0) % 8
    return ((xi >> shift) & 1).astype(compute_dtype)


def _fused_kernel(a_idx, t_mat, a_hbm, tile_ref, out_ref, a_buf, sems,
                  *, G: int, transpose: bool, compute_dtype):
    """grid (R, U): r = union-class row, u = union slot (innermost, the
    reduction dim — the out block stays VMEM-resident across it)."""
    r = pl.program_id(0)
    u = pl.program_id(1)
    n_u = pl.num_programs(1)

    def a_dma(slot, uu, g):
        return pltpu.make_async_copy(
            a_hbm.at[a_idx[r, g, uu]], a_buf.at[slot, g],
            sems.at[slot, g])

    @pl.when(u == 0)
    def _():
        for g in range(G):
            a_dma(0, 0, g).start()
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(u + 1 < n_u)
    def _():
        for g in range(G):
            a_dma((u + 1) % 2, u + 1, g).start()

    slot = u % 2
    tile = tile_ref[0]  # [S, F] fwd / [T, F] bwd, compute dtype
    # contract over s (fwd: out[t,f] += A[t,s] F[s,f]) or over t (bwd:
    # out[s,f] += A[t,s] g[t,f]); square tiles, so both emit [T, F]
    dims = (((0,), (0,)), ((), ())) if transpose \
        else (((1,), (0,)), ((), ()))
    for g in range(G):
        a_dma(slot, u, g).wait()
        a = _unpack_sublane(a_buf[slot, g], compute_dtype)
        out_ref[0, g] += jax.lax.dot_general(
            a, tile, dims, preferred_element_type=jnp.float32)


def fused_union_apply(a_bits_t: jax.Array, a_idx: jax.Array,
                      t_mat: jax.Array, tiles: jax.Array, tile_size: int,
                      transpose: bool = False,
                      interpret: bool = False,
                      vma: Optional[frozenset] = None) -> jax.Array:
    """One union-width class: a_idx [R, G, U] int32 (pad -> the zero
    block B), t_mat [R, U] int32 (pad -> the zero tile), a_bits_t
    [B+1, T//8, S] uint8 (zero block appended), tiles
    [n_tiles+1, S|T, F] in the compute dtype -> [R, G, T, F] f32.
    `vma` = the enclosing shard_map's varying mesh axes (check_vma
    needs the pallas output annotated)."""
    R, G, U = a_idx.shape
    T = tile_size
    F = tiles.shape[-1]
    f_pad = -F % _LANE
    if f_pad:
        tiles = jnp.pad(tiles, ((0, 0), (0, 0), (0, f_pad)))
    fp = F + f_pad
    compute_dtype = tiles.dtype

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R, U),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # a_bits_t: manual DMA
            pl.BlockSpec(
                (1, tiles.shape[1], fp),
                lambda r, u, a_ref, t_ref: (t_ref[r, u], 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, G, T, fp), lambda r, u, a_ref, t_ref: (r, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, G, T // 8, T), jnp.uint8),
            pltpu.SemaphoreType.DMA((2, G)),
        ],
    )
    from ..compat import shape_dtype_struct

    sds = (shape_dtype_struct((R, G, T, fp), jnp.float32, vma=vma)
           if vma is not None
           else jax.ShapeDtypeStruct((R, G, T, fp), jnp.float32))
    out = pl.pallas_call(
        functools.partial(_fused_kernel, G=G, transpose=transpose,
                          compute_dtype=compute_dtype),
        grid_spec=grid_spec,
        out_shape=sds,
        interpret=interpret,
    )(a_idx, t_mat, a_bits_t, tiles)
    return out[..., :F] if f_pad else out


def fused_dense_apply_grouped(a_bits_t, classes, inv, tiles, T, out_rows,
                              n_feat, transpose=False, interpret=False,
                              vma=None):
    """Drop-in fused replacement for block_spmm._dense_apply_grouped:
    same class/inv layout, the per-class compute is the Pallas kernel.
    Row-chunking bounds the scalar-prefetch tables (SMEM-resident
    a_idx/t_mat), not an HBM transient — there is none."""
    from .block_spmm import _apply_classes, _DENSE_CHUNK_ELEMS

    # pad F once, OUTSIDE the per-chunk compute: inside _apply_classes's
    # scan the pad would recopy the full tile buffer every chunk
    f_pad = -n_feat % _LANE
    if f_pad:
        tiles = jnp.pad(tiles, ((0, 0), (0, 0), (0, f_pad)))

    def compute(ai, ti):
        out = fused_union_apply(a_bits_t, ai, ti, tiles, T,
                                transpose=transpose,
                                interpret=interpret, vma=vma)
        return out[..., :n_feat] if f_pad else out

    def per_row_elems(mats):
        # target ~64 KB of int32 scalar-prefetch per chunk: rpc =
        # _DENSE_CHUNK_ELEMS // per_row_elems ~= 16384 // (G * U)
        g, u = mats[0].shape[1], mats[0].shape[2]
        return max(1, (_DENSE_CHUNK_ELEMS * g * u) // 16384)

    return _apply_classes(
        classes, compute, per_row_elems,
        (a_bits_t.shape[0] - 1, tiles.shape[0] - 1),
        inv, T, n_feat, out_rows)
