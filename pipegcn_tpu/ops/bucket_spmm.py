"""Degree-bucketed dense SpMM (mean aggregation) — scatter-free.

A second TPU-native replacement for DGL's SpMM kernel (reference
module/layer.py:47-49), built for the regime where the per-device shard
does NOT fit VMEM. XLA lowers
`segment_sum` to scatter-add, which serializes badly on TPU; this
formulation removes every scatter from both the forward AND the backward:

  1. Host: bucket destination rows by ~x1.5-ladder local degree. Each
     bucket b holds a padded neighbor-index matrix idx_b of shape
     [n_b, D_b] (D_b = bucket width; pad entries point at a zero
     sentinel row appended to fbuf).
  2. Device: per bucket, out_b = sum over axis 1 of fbuf_pad[idx_b]
     — a gather followed by a dense reduction the TPU vectorizes.
  3. Results concatenate in bucket order; one final gather by a
     precomputed inverse permutation restores destination order.

The backward needs d_fbuf[src] += g[dst]/deg[dst] summed over edges —
itself an SpMM with edge roles swapped — so the host also builds
transpose tables (bucket by *source* out-degree) and the custom VJP runs
the same scatter-free kernel in the other direction, accumulating in f32.

Padding overhead is bounded by 1.5x (the _ladder_rungs width steps)
and is ~1.2x on real degree distributions. All shapes are static; per-device tables
are padded to shared maxima so one traced program serves every device in
shard_map.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# bound on the materialized [rows, D_b, F] gather per bucket chunk
# (elements, not bytes): 32M elems = 128 MB in f32, 64 MB in bf16
DEFAULT_CHUNK_ELEMS = 32 * 1024 * 1024

# TPU row-gather fast path: measured on v5e, gathering rows of <= 256
# bytes runs at ~400-460M rows/s while wider rows fall off a cliff to
# ~75-80M rows/s (5-6x). Rows are therefore processed in feature slabs
# of SLAB_BYTES, each slab materialized as its own compact [R, slab]
# operand (a strided slice of the wide buffer does NOT trigger the fast
# path) via a lax.scan over the slab axis.
SLAB_BYTES = 256

# streaming-slab run length: a maximal +1-consecutive run in a gather
# index stream is chopped into fixed slabs of this many rows, each
# executed as one lax.dynamic_slice streaming copy instead of 8 random
# row gathers. Fixed length keeps every slab the same shape (one
# traced copy loop); 8 rows x 256 B is one fast-path gather row's
# worth of contiguous HBM traffic per issued copy.
SLAB_RUN = 8


def _find_runs(flat: np.ndarray, sentinel: int):
    """(starts, lengths) of the maximal +1-consecutive runs among
    non-sentinel entries of a flat gather index stream (host-side).
    Sentinel entries (table padding) break runs and are not counted."""
    real = flat < sentinel
    n = flat.shape[0]
    chain = np.zeros(n, bool)
    if n > 1:
        chain[1:] = real[1:] & real[:-1] & (flat[1:] == flat[:-1] + 1)
    starts = np.nonzero(real & ~chain)[0]
    ends = np.nonzero(real & ~np.concatenate([chain[1:], [False]]))[0]
    return starts, ends - starts + 1


def build_slab_plan(stacked: np.ndarray, sentinel: int,
                    slab_len: int = SLAB_RUN):
    """Streaming-slab plan for one bucket's stacked gather table
    [P, cap, w] — the table-build-time half of the slab-gather path.

    Detects contiguous index runs in each part's row-major flattened
    stream (the order the device materializes messages in) and chops
    runs of >= slab_len into fixed-length slabs. Returns None when no
    part has a qualifying run, else a dict of arrays:

      res [P, cap, w] — the residue table: slab-covered entries
        replaced by the zero-row sentinel, so the clipped-take path
        reads them as cheap repeated sentinel rows and the slab copies
        overwrite them with the real data;
      src [P, S] / pos [P, S] — each slab's first source row and its
        flat position in the [cap*w] message stream (S = max slab
        count across parts; padding entries write src row 0 into the
        scratch slab PAST the stream end — _slab_gather_sum appends
        one, so the loop bound stays static and shard_map-legal);
      cnt [P] — real slab count per part (validation/stats only; the
        device loop runs all S iterations, padding lands in scratch).
    """
    P, cap, w = stacked.shape
    res = np.array(stacked, copy=True)
    srcs, poss = [], []
    for p in range(P):
        flat = stacked[p].reshape(-1).astype(np.int64)
        starts, lens = _find_runs(flat, sentinel)
        ks = lens // slab_len
        sel = ks > 0
        starts, ks = starts[sel], ks[sel]
        if starts.size:
            within = (np.arange(int(ks.sum()))
                      - np.repeat(np.cumsum(ks) - ks, ks)) * slab_len
            pos = np.repeat(starts, ks) + within
            rflat = res[p].reshape(-1)
            cov = (pos[:, None]
                   + np.arange(slab_len)[None, :]).reshape(-1)
            src = flat[pos]
            rflat[cov] = sentinel
            res[p] = rflat.reshape(cap, w)
        else:
            pos = np.zeros(0, np.int64)
            src = np.zeros(0, np.int64)
        srcs.append(src)
        poss.append(pos)
    s_cap = max(s.shape[0] for s in srcs)
    if s_cap == 0:
        return None

    def pad(a, fill):
        if a.shape[0] < s_cap:
            a = np.concatenate(
                [a, np.full(s_cap - a.shape[0], fill, np.int64)])
        return a.astype(np.int32)

    # padding slabs copy source row 0 into the scratch slab at flat
    # position cap*w (one past the stream; the device buffer appends
    # SLAB_RUN scratch rows there), so every part runs the same static
    # S iterations and the dead writes land out of band
    return {
        "res": res,
        "src": np.stack([pad(s, 0) for s in srcs]),
        "pos": np.stack([pad(p_, cap * w) for p_ in poss]),
        "cnt": np.asarray([s.shape[0] for s in srcs], np.int32),
    }


def gather_contiguity(tables, n_src_rows: int,
                      slab_len: int = SLAB_RUN):
    """Host-side contiguity stat of the forward gather streams of a
    sharded table dict (bucket or block-remainder): mean +1-run length
    and the fraction of real gather entries a slab plan of `slab_len`
    would cover. Cheap O(tables) — the number the reorder lever is
    supposed to move, reported by bench next to the epoch anatomy."""
    n_real = n_runs = covered = 0
    for k in sorted(tables):
        if not (k.startswith("bkt_fwd_") or k.startswith("blkrem_fwd_")) \
                or k.endswith("inv"):
            continue
        t = np.asarray(tables[k])
        for p in range(t.shape[0]):
            _, lens = _find_runs(t[p].reshape(-1).astype(np.int64),
                                 n_src_rows)
            n_real += int(lens.sum())
            n_runs += int(lens.shape[0])
            covered += int(((lens // slab_len) * slab_len).sum())
    return {
        "mean_run_len": round(n_real / max(n_runs, 1), 4),
        "slab_frac": round(covered / max(n_real, 1), 6),
    }


def _ladder_rungs():
    """The single source of the bucket-width progression: ~x1.5 steps
    [1, 2, 3, 4, 6, 9, 13, ...]. This bounds bucket padding at 1.5x
    worst-case (~1.2x on real degree distributions) vs 2x/1.33x for
    power-of-2 steps — measured at Reddit scale the pow-2 tables
    carried 1.34x remainder and 1.43x dense-K padding, ~0.35 s/epoch
    of pure pad work. A longer ladder only adds a few extra (cheap)
    bucket launches."""
    w = 1
    while True:
        yield w
        w = max(w + 1, (w * 3) // 2)


def _bucket_widths(max_deg: int, min_width: int = 0) -> List[int]:
    """Ladder rungs up to (and including) the first >= max_deg.

    `min_width` truncates the ladder from BELOW: rungs narrower than it
    are dropped, merging every low-degree row into the first surviving
    rung (the bucket-merge launch/transient lever — fewer per-bucket
    gather launches and concat operands at a padding cost bounded by
    min_width per merged row). 0 keeps the full ladder."""
    widths = []
    for w in _ladder_rungs():
        if w < min_width:
            continue
        widths.append(w)
        if w >= max_deg:
            return widths


def ladder_prefix(n: int) -> List[int]:
    """First n rungs (the sharded builders regenerate shared ladders
    of a given length from the same generator)."""
    return list(itertools.islice(_ladder_rungs(), n))


def build_tables_for_edges(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_out: int,
    n_src_rows: int,
    widths: Sequence[int],
) -> Tuple[List[np.ndarray], np.ndarray, List[int]]:
    """Bucket tables for one device's edge list (any order; pad edges
    must have dst == n_out and are dropped).

    Returns (idx_mats, inv_perm, counts):
      idx_mats[b]: [n_b, widths[b]] int32 into fbuf_pad rows, pad =
        n_src_rows (the zero sentinel row);
      inv_perm: [n_out] int32 into the concatenated bucket output (rows
        with zero degree point at its final zero sentinel row);
      counts[b]: real rows in bucket b.
    """
    real = edge_dst < n_out
    src = edge_src[real].astype(np.int64)
    dst = edge_dst[real].astype(np.int64)
    from ..native import stable_argsort

    order = stable_argsort(dst)
    src, dst = src[order], dst[order]
    row_ptr = np.searchsorted(dst, np.arange(n_out + 1))
    deg = (row_ptr[1:] - row_ptr[:-1]).astype(np.int64)

    widths_arr = np.asarray(widths, dtype=np.int64)
    # bucket id = first width >= deg (deg 0 handled separately)
    bid = np.searchsorted(widths_arr, np.maximum(deg, 1))
    bid = np.minimum(bid, len(widths) - 1)

    idx_mats: List[np.ndarray] = []
    counts: List[int] = []
    inv_perm = np.full(n_out, -1, dtype=np.int64)
    offset = 0
    for b, w in enumerate(widths):
        rows = np.nonzero((bid == b) & (deg > 0))[0]
        n_b = rows.shape[0]
        mat = np.full((n_b, w), n_src_rows, dtype=np.int32)
        # fill each row's neighbors from CSR
        if n_b:
            starts = row_ptr[rows]
            lens = deg[rows]
            # vectorized ragged fill: flat positions (i, j<lens[i])
            j = np.arange(w)[None, :]
            mask = j < lens[:, None]
            flat_src_pos = (starts[:, None] + j)[mask]
            mat[np.nonzero(mask)[0], np.nonzero(mask)[1]] = src[
                flat_src_pos
            ].astype(np.int32)
            inv_perm[rows] = offset + np.arange(n_b)
        idx_mats.append(mat)
        counts.append(n_b)
        offset += n_b
    # zero-degree rows -> final zero sentinel row of the concat output
    inv_perm[inv_perm < 0] = offset
    return idx_mats, inv_perm.astype(np.int32), counts


def _slab_gather_sum(fbuf_pad, plan, n_b, w, f):
    """One bucket's messages via the streaming-slab plan: the residue
    table gathers the scattered entries (slab-covered positions point
    at the zero sentinel row — cheap repeated reads), then each slab is
    one lax.dynamic_slice streaming copy of SLAB_RUN contiguous source
    rows written over its flat position. The trip count is the STATIC
    cross-part slab cap S (a traced bound would lower to `while`, which
    shard_map's replication checker rejects); padded iterations write
    into the scratch slab appended past the stream end and are sliced
    off below."""
    flat = jnp.take(fbuf_pad, plan["res"].reshape(-1), axis=0,
                    mode="clip")
    n_flat = flat.shape[0]
    buf0 = jnp.concatenate(
        [flat, jnp.zeros((SLAB_RUN, f), flat.dtype)], axis=0)

    def body(i, buf):
        blk = jax.lax.dynamic_slice(fbuf_pad, (plan["src"][i], 0),
                                    (SLAB_RUN, f))
        return jax.lax.dynamic_update_slice(buf, blk,
                                            (plan["pos"][i], 0))

    buf = jax.lax.fori_loop(0, plan["src"].shape[0], body, buf0)
    return buf[:n_flat].reshape(n_b, w, f).astype(jnp.float32) \
        .sum(axis=1)


def bucket_aggregate(
    fbuf: jax.Array,
    idx_mats: Sequence[jax.Array],
    inv_perm: jax.Array,
    chunk_elems: int = DEFAULT_CHUNK_ELEMS,
    chunk_edges: Optional[int] = None,
    slab: Optional[int] = None,
    run_plans: Optional[Sequence[Optional[dict]]] = None,
) -> jax.Array:
    """Scatter-free sum aggregation. fbuf [R, F] (any float dtype);
    returns f32 [n_out, F] where n_out = inv_perm length. idx_mats index
    into fbuf with R itself as the zero-row sentinel.

    `chunk_edges` (the --spmm-chunk edge budget) overrides the default
    element budget: each gather materializes at most ~chunk_edges
    messages.

    Rows wider than SLAB_BYTES are processed per feature slab (see
    SLAB_BYTES note above); `slab` overrides the element width (0
    disables slabbing).

    `run_plans` (per bucket, None entries allowed) switches a bucket to
    the streaming-slab path (_slab_gather_sum) when it fits one chunk;
    chunked buckets keep the original table — the plan's flat
    positions only align to the unchunked message stream.

    Every gather runs with mode='clip' (clamped, no bounds-check
    select): the table indices are in-bounds BY CONSTRUCTION (pad
    entries point at appended zero sentinel rows, validated host-side
    by validate_bucket_tables), and jnp.take's default FILL_OR_DROP
    mode is the one component of this kernel that can FABRICATE NaN
    out of valid data — exactly the failure shape of the epoch-0
    products-scale NaN that appeared on the experimental TPU platform
    but never on CPU (docs/RESILIENCE.md "Numerics")."""
    f = fbuf.shape[-1]
    if slab is None:
        slab = SLAB_BYTES // fbuf.dtype.itemsize
    if slab and f > slab:
        return _slabbed_aggregate(fbuf, idx_mats, inv_perm, chunk_elems,
                                  chunk_edges, slab, run_plans)
    if chunk_edges:
        chunk_elems = chunk_edges * f
    fbuf_pad = jnp.concatenate(
        [fbuf, jnp.zeros((1, f), fbuf.dtype)], axis=0
    )

    outs = []
    for b, mat in enumerate(idx_mats):
        plan = run_plans[b] if run_plans is not None else None
        n_b, w = mat.shape
        if n_b == 0:
            outs.append(jnp.zeros((0, f), jnp.float32))
            continue
        rows_per_chunk = max(1, chunk_elems // max(1, w * f))
        if n_b <= rows_per_chunk:
            if plan is not None:
                outs.append(_slab_gather_sum(fbuf_pad, plan, n_b, w, f))
                continue
            msgs = jnp.take(fbuf_pad, mat, axis=0, mode="clip")
            outs.append(msgs.astype(jnp.float32).sum(axis=1))
            continue
        n_chunks = -(-n_b // rows_per_chunk)
        pad_rows = n_chunks * rows_per_chunk - n_b
        mat_p = jnp.pad(mat, ((0, pad_rows), (0, 0)),
                        constant_values=fbuf.shape[0])
        mat_c = mat_p.reshape(n_chunks, rows_per_chunk, w)

        def body(_, m):
            msgs = jnp.take(fbuf_pad, m, axis=0, mode="clip")
            return None, msgs.astype(jnp.float32).sum(axis=1)

        _, chunks = jax.lax.scan(body, None, mat_c)
        outs.append(chunks.reshape(-1, f)[:n_b])
    res = jnp.concatenate(outs + [jnp.zeros((1, f), jnp.float32)], axis=0)
    return jnp.take(res, inv_perm, axis=0, mode="clip")


def _slabbed_aggregate(fbuf, idx_mats, inv_perm, chunk_elems, chunk_edges,
                       slab, run_plans=None):
    """Run bucket_aggregate per feature slab of `slab` elements, scanning
    over a [S, R, slab] re-layout so each slab is a compact operand.
    run_plans pass straight through: the streaming-slab plan is pure
    row structure, independent of the feature split."""
    r, f = fbuf.shape
    n_s = -(-f // slab)
    pad_f = n_s * slab - f
    if pad_f:
        fbuf = jnp.pad(fbuf, ((0, 0), (0, pad_f)))
    slabs = fbuf.reshape(r, n_s, slab).swapaxes(0, 1)  # [S, R, slab]

    def one(_, sl):
        out = bucket_aggregate(sl, idx_mats, inv_perm, chunk_elems,
                               chunk_edges, slab=0, run_plans=run_plans)
        return None, out

    _, outs = jax.lax.scan(one, None, slabs)  # [S, n_out, slab]
    out = outs.swapaxes(0, 1).reshape(-1, n_s * slab)
    return out[:, :f] if pad_f else out


class BucketPlan:
    """Host-side plan for one device: forward + transpose bucket tables.

    fwd aggregates src->dst (the training SpMM over the [R=n_inner+halo]
    source rows into n_out destination rows); bwd aggregates dst->src for
    the gradient. Tables are numpy; `device_tables()` returns a dict of
    arrays to ship (optionally padded to caps shared across devices).
    """

    def __init__(self, edge_src: np.ndarray, edge_dst: np.ndarray,
                 n_out: int, n_src_rows: int,
                 fwd_widths: Optional[Sequence[int]] = None,
                 bwd_widths: Optional[Sequence[int]] = None):
        real = edge_dst < n_out
        deg_in = np.bincount(edge_dst[real], minlength=n_out)
        deg_out = np.bincount(edge_src[real], minlength=n_src_rows)
        self.fwd_widths = list(
            fwd_widths if fwd_widths is not None
            else _bucket_widths(int(deg_in.max(initial=1)))
        )
        self.bwd_widths = list(
            bwd_widths if bwd_widths is not None
            else _bucket_widths(int(deg_out.max(initial=1)))
        )
        self.n_out = n_out
        self.n_src_rows = n_src_rows
        self.fwd_mats, self.fwd_inv, self.fwd_counts = \
            build_tables_for_edges(edge_src, edge_dst, n_out, n_src_rows,
                                   self.fwd_widths)
        # transpose: swap roles; "destinations" are the source rows
        self.bwd_mats, self.bwd_inv, self.bwd_counts = \
            build_tables_for_edges(edge_dst[real], edge_src[real],
                                   n_src_rows, n_out, self.bwd_widths)


def transport_dtypes(rem_dtype: Optional[str]):
    """(forward, backward) gather-transport dtypes for a remainder/
    bucket transport spec. The gather path is request-rate-bound at
    256-byte rows (SLAB_BYTES note), so BYTES PER FEATURE set the
    row count: fp8 packs 256 features into one 256 B slab — half the
    gathered rows of bf16 at F=256. Activations travel e4m3 (range
    +-448 suits post-norm activations), cotangents e5m2 (gradient
    dynamic range needs exponent bits); accumulation stays f32 either
    way. None = no cast (the activation dtype)."""
    if rem_dtype in (None, "", "none"):
        return None, None
    if rem_dtype == "float8":
        return jnp.float8_e4m3fn, jnp.float8_e5m2
    if rem_dtype == "bfloat16":
        return jnp.bfloat16, jnp.bfloat16
    raise ValueError(f"unknown transport dtype: {rem_dtype!r}")


# finite maxima of the fp8 transport dtypes: they have NO inf, so an
# overflowing astype produces NaN — transport_cast saturates instead
# (the standard fp8 convention). Raw layer-0 features beyond the range
# (use_pp=False / gcn) thus degrade gracefully rather than poisoning
# the epoch with NaN.
_F8_MAX = {jnp.float8_e4m3fn: 448.0, jnp.float8_e5m2: 57344.0}


def transport_cast(x: jax.Array, dt) -> jax.Array:
    """Saturating cast to a transport dtype (identity when dt is
    None); fp8 targets clamp to their finite max first."""
    if dt is None:
        return x
    m = _F8_MAX.get(dt)
    if m is not None:
        x = jnp.clip(x.astype(jnp.float32), -m, m)
    return x.astype(dt)


def amax_transport_cast(x: jax.Array, dt):
    """Amax-clamped fp8 cast (resilience/numerics guardrail): scale the
    tensor by a power of two chosen from its own amax so values land
    mid-range in the fp8 format — instead of the static clamp
    saturating large activations (a silent bias) or small cotangents
    flushing to zero (a silent underflow). Returns ``(y, inv_scale)``;
    the caller multiplies the (linear) aggregation's output by
    ``inv_scale`` to undo it. inv_scale is None when dt is not an fp8
    format (the plain saturating cast applies)."""
    if dt is None:
        return x, None
    m = _F8_MAX.get(dt)
    if m is None:
        return transport_cast(x, dt), None
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf))
    # power-of-two scale targeting half the finite max (headroom for
    # the aggregation's intermediate values); exact to re-divide, so
    # the de-scale introduces no extra rounding. Degenerate amax
    # (zero / non-finite) keeps scale 1 — a NaN input must stay a NaN
    # output for the tripwire, never become a NaN *scale*.
    ok = jnp.isfinite(amax) & (amax > 0)
    s = jnp.where(ok, jnp.exp2(jnp.floor(jnp.log2(
        m / 2.0 / jnp.where(ok, amax, 1.0)))), 1.0)
    y = jnp.clip(xf * s, -m, m).astype(dt)
    return y, 1.0 / s


def make_bucket_spmm_fn(
    fwd_mats: Sequence[jax.Array],
    fwd_inv: jax.Array,
    bwd_mats: Sequence[jax.Array],
    bwd_inv: jax.Array,
    in_deg: jax.Array,
    n_src_rows: int,
    chunk_elems: int = DEFAULT_CHUNK_ELEMS,
    chunk_edges: Optional[int] = None,
    rem_dtype: Optional[str] = None,
    rem_amax: bool = False,
    fwd_plans: Optional[Sequence[Optional[dict]]] = None,
    bwd_plans: Optional[Sequence[Optional[dict]]] = None,
):
    """Differentiable mean-aggregation closure: f(fbuf [R, F]) ->
    f32 [n_out, F]; backward is the transpose bucket aggregation, f32
    accumulation, cotangent cast back to fbuf's dtype. `rem_dtype`
    optionally narrows the GATHER TRANSPORT (see transport_dtypes) —
    the one cast before aggregation halves gathered rows at F=256.
    `rem_amax` swaps the static saturating fp8 cast for the
    amax-clamped one (amax_transport_cast): per-tensor power-of-two
    scaling into mid-range, inverse applied after aggregation.
    `fwd_plans`/`bwd_plans` are per-bucket streaming-slab plans
    (bucket_aggregate run_plans)."""
    deg_col = in_deg[:, None]
    fwd_dt, bwd_dt = transport_dtypes(rem_dtype)

    def _cast(x, dt):
        if rem_amax:
            return amax_transport_cast(x, dt)
        return transport_cast(x, dt), None

    @jax.custom_vjp
    def f(fbuf):
        y, inv = _cast(fbuf, fwd_dt)
        out = bucket_aggregate(y, fwd_mats, fwd_inv, chunk_elems,
                               chunk_edges,
                               run_plans=fwd_plans) / deg_col
        return out * inv if inv is not None else out

    def fwd(fbuf):
        return f(fbuf), jnp.zeros((0,), fbuf.dtype)

    def bwd(proto, g):
        # transpose aggregation; cotangents travel in the transport
        # dtype (default: the activation dtype — half the gather
        # traffic and double the slab width vs f32, same precision as
        # the halo exchange), while bucket_aggregate still accumulates
        # in f32. The transport cast comes straight from the f32
        # value — never through an intermediate rounding.
        gd32 = g.astype(jnp.float32) / deg_col
        if bwd_dt is not None:
            gd, inv = _cast(gd32, bwd_dt)
        else:
            gd, inv = gd32.astype(proto.dtype), None
        d_fbuf = bucket_aggregate(gd, bwd_mats, bwd_inv, chunk_elems,
                                  chunk_edges, run_plans=bwd_plans)
        if inv is not None:
            d_fbuf = d_fbuf * inv
        return (d_fbuf[:n_src_rows].astype(proto.dtype),)

    f.defvjp(fwd, bwd)
    return f


def build_sharded_bucket_tables(sg, chunk_elems: int = DEFAULT_CHUNK_ELEMS,
                                min_width: int = 0, slab: bool = False,
                                plan_cache: Optional[dict] = None,
                                dirty: Optional[Sequence[int]] = None
                                ) -> Dict[str, np.ndarray]:
    """Stacked per-device tables for shard_map (leading device axis),
    padded to shared bucket widths and per-bucket row caps so the traced
    program is identical on every device.

    `min_width` merges every bucket narrower than it into the first
    surviving ladder rung (see _bucket_widths) — the bucket-merge
    launch-overhead lever, surfaced as --bucket-merge.

    `slab` additionally emits streaming-slab plans (build_slab_plan)
    for every bucket with a qualifying contiguous run, under keys
    'bkt_{fwd,bwd}{res,src,pos,cnt}_<b>' (no underscore after the
    side, so the plain-table key predicates never match them).

    `plan_cache` (a mutable dict, updated in place) with `dirty` (shard
    ids whose edges changed) is the streaming-delta fast path: per-
    shard degree maxima and BucketPlans are recomputed only for dirty
    shards, clean shards reuse the cached ones — the O(E_r) per-shard
    plan builds are the dominant cost, and a delta batch touches few
    shards. Cached plans are only valid at the SAME width ladder: if
    the global max degree moves the ladder, every plan rebuilds (the
    resulting tables are identical to a cache-free build either way).

    Returns {'bkt_fwd_<b>': [P, cap_b, w_b], 'bkt_fwd_inv': [P, n_max],
             'bkt_bwd_<b>': ..., 'bkt_bwd_inv': [P, R]}.
    """
    P = sg.num_parts
    n_src_rows = sg.n_max + sg.halo_size
    cache = plan_cache if plan_cache is not None else {}
    stale = set(range(P)) if dirty is None or not cache else set(dirty)
    if cache.get("shape") != (sg.n_max, n_src_rows) or \
            cache.get("min_width") != min_width:
        cache.clear()
        stale = set(range(P))

    # shared width ladders from global max degrees (per-shard maxima
    # cached; only dirty shards rescan their edges)
    degs = cache.get("degs", [None] * P)
    degs += [None] * (P - len(degs))
    for r in range(P):
        if degs[r] is not None and r not in stale:
            continue
        real = sg.edge_dst[r] < sg.n_max
        mi, mo = 1, 1
        if real.any():
            di = np.bincount(sg.edge_dst[r][real], minlength=sg.n_max)
            do = np.bincount(sg.edge_src[r][real], minlength=n_src_rows)
            mi = max(1, int(di.max(initial=1)))
            mo = max(1, int(do.max(initial=1)))
        degs[r] = (mi, mo)
    max_in = max(d[0] for d in degs)
    max_out = max(d[1] for d in degs)
    fw = _bucket_widths(max_in, min_width)
    bw = _bucket_widths(max_out, min_width)
    if cache.get("widths") != (tuple(fw), tuple(bw)):
        stale = set(range(P))  # ladder moved: every plan is invalid

    old_plans = cache.get("plans", [None] * P)
    old_plans += [None] * (P - len(old_plans))
    plans = [
        old_plans[r] if old_plans[r] is not None and r not in stale
        else BucketPlan(sg.edge_src[r], sg.edge_dst[r], sg.n_max,
                        n_src_rows, fwd_widths=fw, bwd_widths=bw)
        for r in range(P)
    ]
    if plan_cache is not None:
        plan_cache.update(
            shape=(sg.n_max, n_src_rows), min_width=min_width,
            widths=(tuple(fw), tuple(bw)), degs=degs, plans=plans)
    fwd_caps = [max(p.fwd_counts[b] for p in plans) for b in range(len(fw))]
    bwd_caps = [max(p.bwd_counts[b] for p in plans) for b in range(len(bw))]

    def pad_to_cap(mat: np.ndarray, cap: int, sentinel: int) -> np.ndarray:
        # append all-sentinel rows up to the shared cap (their output is
        # ignored: no inv_perm entry points into the pad range)
        if mat.shape[0] == cap:
            return mat
        return np.pad(mat, ((0, cap - mat.shape[0]), (0, 0)),
                      constant_values=sentinel)

    def reoffset_inv(inv: np.ndarray, counts: Sequence[int],
                     caps: Sequence[int]) -> np.ndarray:
        # inv_perm was built with per-device bucket offsets (cumsum of
        # counts); shift each bucket's range to the shared cap layout
        inv = inv.astype(np.int64)
        out = np.full_like(inv, sum(caps))  # default: zero sentinel row
        off_old = 0
        off_new = 0
        for n_b, cap in zip(counts, caps):
            in_b = (inv >= off_old) & (inv < off_old + n_b)
            out[in_b] = inv[in_b] - off_old + off_new
            off_old += n_b
            off_new += cap
        return out.astype(np.int32)

    tables: Dict[str, np.ndarray] = {
        "bkt_fwd_inv": np.stack([
            reoffset_inv(p.fwd_inv, p.fwd_counts, fwd_caps) for p in plans
        ]),
        "bkt_bwd_inv": np.stack([
            reoffset_inv(p.bwd_inv, p.bwd_counts, bwd_caps) for p in plans
        ]),
    }
    # zero-padded bucket index keeps lexicographic key order == width
    # order (bucket ladders are < 100 wide: 2^99 degrees is beyond any
    # graph)
    for b in range(len(fw)):
        if fwd_caps[b]:
            tables[f"bkt_fwd_{b:02d}"] = np.stack(
                [pad_to_cap(p.fwd_mats[b], fwd_caps[b], n_src_rows)
                 for p in plans]
            )
    for b in range(len(bw)):
        if bwd_caps[b]:
            tables[f"bkt_bwd_{b:02d}"] = np.stack(
                [pad_to_cap(p.bwd_mats[b], bwd_caps[b], sg.n_max)
                 for p in plans]
            )
    if slab:
        add_slab_plans(tables, ("bkt_fwd", n_src_rows),
                       ("bkt_bwd", sg.n_max))
    validate_bucket_tables(tables, sg.n_max, n_src_rows)
    return tables


def add_slab_plans(tables: Dict[str, np.ndarray], *stems) -> int:
    """Emit streaming-slab plan keys into a stacked table dict for every
    plain bucket table under the given (stem, sentinel) pairs, e.g.
    ('bkt_fwd', n_src_rows). A table 'bkt_fwd_03' with qualifying runs
    gains 'bkt_fwdres_03' / 'bkt_fwdsrc_03' / 'bkt_fwdpos_03' /
    'bkt_fwdcnt_03'. Returns the number of buckets that got a plan."""
    emitted = 0
    for stem, sentinel in stems:
        for k in [k for k in tables if k.startswith(f"{stem}_")
                  and not k.endswith("inv")]:
            b = k.rsplit("_", 1)[1]
            plan = build_slab_plan(tables[k], sentinel)
            if plan is None:
                continue
            tables[f"{stem}res_{b}"] = plan["res"]
            tables[f"{stem}src_{b}"] = plan["src"]
            tables[f"{stem}pos_{b}"] = plan["pos"]
            tables[f"{stem}cnt_{b}"] = plan["cnt"]
            emitted += 1
    return emitted


def extract_run_plans(d: Dict[str, jax.Array], stem: str):
    """Per-bucket run_plans list (for bucket_aggregate) from a device
    table dict, aligned with the `{stem}_<b>` plain tables in sorted
    key order; None when no bucket under this stem has a plan."""
    plans = []
    for k in sorted(d):
        if not k.startswith(f"{stem}_") or k.endswith("inv"):
            continue
        b = k.rsplit("_", 1)[1]
        if f"{stem}res_{b}" in d:
            plans.append({"res": d[f"{stem}res_{b}"],
                          "src": d[f"{stem}src_{b}"],
                          "pos": d[f"{stem}pos_{b}"],
                          "cnt": d[f"{stem}cnt_{b}"]})
        else:
            plans.append(None)
    return plans if any(p is not None for p in plans) else None


def validate_bucket_tables(tables: Dict[str, np.ndarray], n_max: int,
                           n_src_rows: int) -> None:
    """Host-side bounds check of sharded bucket tables ([P, ...] device
    axis leading): every index must lie in [0, bound] where bound is
    the consuming gather's zero-sentinel row. The device kernel gathers
    with mode='clip' ON THE STRENGTH OF THIS CHECK — an out-of-bounds
    index from a build bug or a rotted cache must surface HERE as a
    named ValueError at build/load time, never as a silently-clamped
    wrong row (or, under the previous fill-mode gathers, a NaN minted
    mid-epoch). O(tables) numpy min/max — noise next to the O(E)
    build."""
    fwd_rows = sum(int(t.shape[-2]) for k, t in tables.items()
                   if k.startswith("bkt_fwd_") and not k.endswith("inv"))
    bwd_rows = sum(int(t.shape[-2]) for k, t in tables.items()
                   if k.startswith("bkt_bwd_") and not k.endswith("inv"))
    for k, t in tables.items():
        if k == "bkt_fwd_inv":
            hi = fwd_rows          # + the appended zero sentinel row
        elif k == "bkt_bwd_inv":
            hi = bwd_rows
        elif k.startswith("bkt_fwd_"):
            hi = n_src_rows        # fbuf_pad's zero sentinel row
        elif k.startswith("bkt_bwd_"):
            hi = n_max
        elif k.startswith("bkt_fwdres_"):
            hi = n_src_rows
        elif k.startswith("bkt_bwdres_"):
            hi = n_max
        elif k.startswith(("bkt_fwdsrc_", "bkt_bwdsrc_")):
            # a slab streams SLAB_RUN real rows starting at src
            base = n_src_rows if "fwd" in k else n_max
            hi = max(0, base - SLAB_RUN)
        elif k.startswith(("bkt_fwdpos_", "bkt_bwdpos_")):
            # real slabs end inside the [cap*w] stream; padding points
            # AT cap*w exactly — the appended scratch slab
            res = tables[k.replace("pos_", "res_")]
            hi = int(res.shape[-2]) * int(res.shape[-1])
        elif k.startswith(("bkt_fwdcnt_", "bkt_bwdcnt_")):
            hi = int(tables[k.replace("cnt_", "src_")].shape[-1])
        else:
            continue
        a = np.asarray(t)
        lo_v = int(a.min(initial=0))
        hi_v = int(a.max(initial=0))
        if lo_v < 0 or hi_v > hi:
            raise ValueError(
                f"bucket table {k!r} holds out-of-bounds indices "
                f"[{lo_v}, {hi_v}] (valid range [0, {hi}]): corrupt "
                f"table cache or a table-build bug — rebuild the "
                f"partition artifact's cached tables")


def make_device_bucket_spmm_fn(d: Dict[str, jax.Array], in_deg: jax.Array,
                               n_src_rows: int,
                               chunk_elems: int = DEFAULT_CHUNK_ELEMS,
                               chunk_edges: Optional[int] = None,
                               rem_dtype: Optional[str] = None,
                               rem_amax: bool = False):
    """Bind the per-device blocks of build_sharded_bucket_tables (call
    inside shard_map, after stripping the leading device axis) into the
    differentiable closure."""
    fwd_mats = [d[k] for k in sorted(d) if k.startswith("bkt_fwd_")
                and not k.endswith("inv")]
    bwd_mats = [d[k] for k in sorted(d) if k.startswith("bkt_bwd_")
                and not k.endswith("inv")]
    return make_bucket_spmm_fn(
        fwd_mats, d["bkt_fwd_inv"], bwd_mats, d["bkt_bwd_inv"],
        in_deg, n_src_rows, chunk_elems, chunk_edges, rem_dtype,
        rem_amax, fwd_plans=extract_run_plans(d, "bkt_fwd"),
        bwd_plans=extract_run_plans(d, "bkt_bwd"),
    )
