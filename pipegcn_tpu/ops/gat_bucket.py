"""Scatter-free GAT attention aggregation on the bucket formulation.

The GAT extension previously ran only on the raw-edge segment path
(19.8 s/epoch-class at Reddit scale — three scatter passes over E
edges). This kernel carries the per-edge attention weight through the
same degree-bucket tables the mean path uses (ops/bucket_spmm.py),
removing every scatter:

  - A bucket row holds ALL in-neighbors of one destination (padded to
    the bucket width), so the edge-softmax max-shift, normalizer and
    weighted sum are plain row-wise reductions over the bucket axis —
    no segment_max/segment_sum anywhere, and no separate max pass.
  - Attention logits l_e = leaky(el[src] + er[dst]) decompose into a
    NARROW el gather ([*, H] rows of 4H bytes ride the fast row-gather
    path, docs/PERF_NOTES.md) plus a row-local er term; the expensive
    part stays the single wide message gather the mean path also pays.
  - The backward recomputes alpha in both orientations from row-wise
    stats (m, s, rho) instead of materializing an [E, H] alpha tensor
    (~GBs at Reddit scale): the dst-keyed pass produces d_er, the
    src-keyed transpose pass produces d_z and d_el, each with one wide
    gather + narrow stat gathers. Treating the max-shift m as constant
    is EXACT (the normalized output is invariant to it).

Weighted-edge analogue of the reference's `update_all` with per-edge
weights (reference module/layer.py:47-49); the GAT model family itself
is a framework extension (models/sage.py:_gat_layer defines the
semantics this kernel must reproduce bit-for-bit up to reduction
order).

Sentinel conventions (NaN-free by construction):
  z/g pad row        -> zeros       (contributes 0 to sums)
  el pad row         -> -inf        (alpha = exp(-inf - m) = 0)
  dst-stats pad row  -> m=+inf, s=1 (alpha = 0, no 0/0)
All shapes static; per-device tables pad to shared caps so one traced
program serves every device in shard_map.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .bucket_spmm import (
    DEFAULT_CHUNK_ELEMS,
    SLAB_BYTES,
    BucketPlan,
    _bucket_widths,
)


# ---------------------------------------------------------------------
# host-side table build


def _rows_for_buckets(inv: np.ndarray, counts: Sequence[int]
                      ) -> List[np.ndarray]:
    """Per-bucket destination ids, in bucket-position order — ONE
    argsort of inv, split by counts (inv holds offset + arange(n_b)
    per bucket and a trailing sentinel for zero-degree rows, so the
    ascending order of inv values IS the bucket concatenation order)."""
    order = np.argsort(inv, kind="stable")
    out = []
    off = 0
    for n_b in counts:
        out.append(order[off:off + n_b].astype(np.int32))
        off += n_b
    return out


def build_sharded_gat_tables(sg) -> Dict[str, np.ndarray]:
    """Stacked per-device attention-bucket tables (leading device axis).

    Same bucket structure as build_sharded_bucket_tables plus, per
    bucket, the ROW ids (which destination / source row each bucket row
    belongs to) — the attention kernel needs them to add the row-local
    logit term and to gather per-destination softmax stats in the
    transpose pass. Keys:

      gat_fwd_<b>   [P, cap_b, w_b] in-neighbor ids (sentinel R)
      gat_fwd_rows_<b> [P, cap_b]   dst ids        (sentinel n_max)
      gat_fwd_inv   [P, n_max]      cap-layout concat positions
      gat_bwd_<b>   [P, cap_b, w_b] out-neighbor (dst) ids (sentinel n_max)
      gat_bwd_rows_<b> [P, cap_b]   src ids        (sentinel R)
      gat_bwd_inv   [P, R]
    """
    P = sg.num_parts
    n_src_rows = sg.n_max + sg.halo_size

    max_in, max_out = 1, 1
    for r in range(P):
        real = sg.edge_dst[r] < sg.n_max
        if real.any():
            di = np.bincount(sg.edge_dst[r][real], minlength=sg.n_max)
            do = np.bincount(sg.edge_src[r][real], minlength=n_src_rows)
            max_in = max(max_in, int(di.max(initial=1)))
            max_out = max(max_out, int(do.max(initial=1)))
    fw = _bucket_widths(max_in)
    bw = _bucket_widths(max_out)

    plans = [
        BucketPlan(sg.edge_src[r], sg.edge_dst[r], sg.n_max, n_src_rows,
                   fwd_widths=fw, bwd_widths=bw)
        for r in range(P)
    ]
    fwd_caps = [max(p.fwd_counts[b] for p in plans) for b in range(len(fw))]
    bwd_caps = [max(p.bwd_counts[b] for p in plans) for b in range(len(bw))]

    def pad_mat(mat, cap, sentinel):
        if mat.shape[0] == cap:
            return mat
        return np.pad(mat, ((0, cap - mat.shape[0]), (0, 0)),
                      constant_values=sentinel)

    def pad_rows(rows, cap, sentinel):
        if rows.shape[0] == cap:
            return rows
        return np.pad(rows, (0, cap - rows.shape[0]),
                      constant_values=sentinel)

    def reoffset(inv, counts, caps):
        # vectorized bucket lookup: one searchsorted over the count
        # boundaries instead of a full-array mask per bucket
        inv = inv.astype(np.int64)
        bounds = np.zeros(len(counts) + 1, np.int64)
        np.cumsum(counts, out=bounds[1:])
        starts_new = np.zeros(len(caps), np.int64)
        np.cumsum(caps[:-1], out=starts_new[1:])
        b = np.clip(np.searchsorted(bounds, inv, side="right") - 1,
                    0, len(counts) - 1)
        out = np.where(inv >= bounds[-1], int(sum(caps)),
                       inv - bounds[b] + starts_new[b])
        return out.astype(np.int32)

    # one O(n) scan per plan/orientation (not per bucket)
    fwd_rows = [_rows_for_buckets(p.fwd_inv, p.fwd_counts) for p in plans]
    bwd_rows = [_rows_for_buckets(p.bwd_inv, p.bwd_counts) for p in plans]

    tables: Dict[str, np.ndarray] = {
        "gat_fwd_inv": np.stack([
            reoffset(p.fwd_inv, p.fwd_counts, fwd_caps) for p in plans]),
        "gat_bwd_inv": np.stack([
            reoffset(p.bwd_inv, p.bwd_counts, bwd_caps) for p in plans]),
    }
    for b in range(len(fw)):
        if not fwd_caps[b]:
            continue
        tables[f"gat_fwd_{b:02d}"] = np.stack(
            [pad_mat(p.fwd_mats[b], fwd_caps[b], n_src_rows)
             for p in plans])
        tables[f"gat_fwd_rows_{b:02d}"] = np.stack(
            [pad_rows(r[b], fwd_caps[b], sg.n_max) for r in fwd_rows])
    for b in range(len(bw)):
        if not bwd_caps[b]:
            continue
        tables[f"gat_bwd_{b:02d}"] = np.stack(
            [pad_mat(p.bwd_mats[b], bwd_caps[b], sg.n_max)
             for p in plans])
        tables[f"gat_bwd_rows_{b:02d}"] = np.stack(
            [pad_rows(r[b], bwd_caps[b], n_src_rows) for r in bwd_rows])
    return tables


# ---------------------------------------------------------------------
# device-side slab helpers


def _slab_layout(F: int, dh: int, itemsize: int) -> Tuple[int, int]:
    """(slab_elems, n_slabs) with every slab either covering WHOLE heads
    (slab = k*dh, k | H) or lying inside ONE head (slab | dh) — the
    invariant _slab_heads and the gather helpers slice by. Guaranteed by
    construction: the whole-head case shrinks k to a divisor of H, the
    sub-head case shrinks slab to a divisor of dh (worst case 1)."""
    slab = SLAB_BYTES // itemsize
    if F <= slab:
        return F, 1
    H = F // dh
    if slab >= dh:
        k = slab // dh
        while H % k:
            k -= 1
        slab = dh * k
    else:
        while dh % slab:
            slab -= 1
    return slab, F // slab


def _make_slabs(x2d: jax.Array, slab: int, n_slabs: int) -> jax.Array:
    """[R, F] -> [S, R, slab]: each slab a compact gather operand (a
    strided slice of the wide buffer does NOT ride the fast row-gather
    path — docs/PERF_NOTES.md)."""
    r = x2d.shape[0]
    return x2d.reshape(r, n_slabs, slab).swapaxes(0, 1)


def _slab_heads(j: int, slab: int, dh: int) -> Tuple[int, int, int]:
    """Static head coverage of slab j: (first_head, n_heads_covered,
    offset_within_head). Either whole heads (offset 0) or a sub-head
    range (n=1)."""
    start = j * slab
    if slab >= dh:
        return start // dh, slab // dh, 0
    return start // dh, 1, start % dh


def _gather_weighted(slabs, idx, w, slab, dh, acc_out):
    """acc_out += sum_D w * msgs, per head. slabs [S, R+1, slab];
    idx [r, D]; w [r, D, H] f32; acc_out [r, H, dh] f32 (functional:
    returns the updated value)."""
    for j in range(slabs.shape[0]):
        msgs = jnp.take(slabs[j], idx, axis=0,
                        mode="clip").astype(jnp.float32)
        h0, nh, off = _slab_heads(j, slab, dh)
        if nh >= 1 and off == 0 and slab >= dh:
            m2 = msgs.reshape(*idx.shape, nh, dh)
            part = jnp.einsum("rdh,rdhf->rhf", w[..., h0:h0 + nh], m2)
            acc_out = acc_out.at[:, h0:h0 + nh, :].add(part)
        else:
            part = jnp.einsum("rd,rdf->rf", w[..., h0], msgs)
            acc_out = acc_out.at[:, h0, off:off + slab].add(part)
    return acc_out


def _gather_contract(slabs, idx, rowvec, slab, dh):
    """c[r, D, H] = sum_f msgs * rowvec (per head). rowvec [r, H, dh]
    f32 — the row-local vector each gathered message dots against."""
    r, D = idx.shape
    H = rowvec.shape[1]
    c = jnp.zeros((r, D, H), jnp.float32)
    for j in range(slabs.shape[0]):
        msgs = jnp.take(slabs[j], idx, axis=0,
                        mode="clip").astype(jnp.float32)
        h0, nh, off = _slab_heads(j, slab, dh)
        if nh >= 1 and off == 0 and slab >= dh:
            m2 = msgs.reshape(r, D, nh, dh)
            part = jnp.einsum("rhf,rdhf->rdh", rowvec[:, h0:h0 + nh], m2)
            c = c.at[..., h0:h0 + nh].add(part)
        else:
            part = jnp.einsum("rf,rdf->rd",
                              rowvec[:, h0, off:off + slab], msgs)
            c = c.at[..., h0].add(part)
    return c


def _gather_weighted_contract(slabs, idx, w, rowvec, slab, dh, acc_out):
    """One gather pass computing BOTH sum_D w*msgs and the per-slot
    contraction c = <rowvec, msgs> (the transpose pass needs both from
    the same messages; gathering once halves its wide traffic)."""
    r, D = idx.shape
    H = rowvec.shape[1]
    c = jnp.zeros((r, D, H), jnp.float32)
    for j in range(slabs.shape[0]):
        msgs = jnp.take(slabs[j], idx, axis=0,
                        mode="clip").astype(jnp.float32)
        h0, nh, off = _slab_heads(j, slab, dh)
        if nh >= 1 and off == 0 and slab >= dh:
            m2 = msgs.reshape(r, D, nh, dh)
            acc_out = acc_out.at[:, h0:h0 + nh, :].add(
                jnp.einsum("rdh,rdhf->rhf", w[..., h0:h0 + nh], m2))
            c = c.at[..., h0:h0 + nh].add(
                jnp.einsum("rhf,rdhf->rdh", rowvec[:, h0:h0 + nh], m2))
        else:
            acc_out = acc_out.at[:, h0, off:off + slab].add(
                jnp.einsum("rd,rdf->rf", w[..., h0], msgs))
            c = c.at[..., h0].add(
                jnp.einsum("rf,rdf->rd",
                           rowvec[:, h0, off:off + slab], msgs))
    return acc_out, c


def _chunked(mat, rows, per, idx_sentinel, row_sentinel):
    """Pad a bucket to a chunk multiple and reshape for lax.scan."""
    n_b = mat.shape[0]
    per = min(per, max(n_b, 1))  # never pad a small bucket UP to the
    n_c = -(-n_b // per)         # chunk budget (that would process
    pad = n_c * per - n_b        # budget-many sentinel rows per bucket)
    if pad:
        mat = jnp.pad(mat, ((0, pad), (0, 0)),
                      constant_values=idx_sentinel)
        rows = jnp.pad(rows, (0, pad), constant_values=row_sentinel)
    return (mat.reshape(n_c, per, mat.shape[1]),
            rows.reshape(n_c, per), n_b)


def _leaky(x, slope):
    return jnp.where(x > 0, x, slope * x)


def _dleaky(x, slope):
    return jnp.where(x > 0, 1.0, slope)


# ---------------------------------------------------------------------
# the differentiable kernel


def make_device_gat_fn(
    d: Dict[str, jax.Array],
    n_dst: int,
    n_src_rows: int,
    n_heads: int,
    slope: float,
    chunk_elems: int = DEFAULT_CHUNK_ELEMS,
    chunk_edges: Optional[int] = None,
    rem_dtype: Optional[str] = None,
):
    """Bind one device's tables (leading axis stripped) into a
    differentiable closure gat(z, el, er) -> [n_dst, H, dh] f32:

        out_d = sum_{e: dst=e} softmax_d(leaky(el[src] + er[dst])) z[src]

    z [R, H, dh] (any float dtype), el [R, H] f32, er [n_dst, H] f32.
    The VJP returns (dz, del, der); everything around the aggregation
    (W matmul, a_src/a_dst products, head merge, bias) stays standard
    autodiff in the model.

    `rem_dtype` narrows the WIDE gather transports only
    (bucket_spmm.transport_dtypes): z values travel e4m3 through the
    forward and both backward contractions (the same quantized values
    everywhere, so the VJP matches the quantized forward), the
    cotangent slabs travel e5m2; attention logits, softmax stats, and
    every accumulation stay f32."""
    from .bucket_spmm import transport_cast, transport_dtypes

    fwd_dt, bwd_dt = transport_dtypes(rem_dtype)
    fwd_keys = sorted(k for k in d if k.startswith("gat_fwd_")
                      and "rows" not in k and not k.endswith("inv"))
    bwd_keys = sorted(k for k in d if k.startswith("gat_bwd_")
                      and "rows" not in k and not k.endswith("inv"))
    fwd = [(d[k], d[k.replace("gat_fwd_", "gat_fwd_rows_")])
           for k in fwd_keys]
    bwd = [(d[k], d[k.replace("gat_bwd_", "gat_bwd_rows_")])
           for k in bwd_keys]
    fwd_inv, bwd_inv = d["gat_fwd_inv"], d["gat_bwd_inv"]
    R = n_src_rows

    def rows_per_chunk(width, unit):
        budget = chunk_edges * unit if chunk_edges else chunk_elems
        return max(1, budget // max(1, width * unit))

    def fwd_pass(z, el, er):
        """One pass: narrow el gather + wide weighted z gather.
        Returns (out [n_dst,H,dh] f32 normalized, m, s [n_dst,H])."""
        H, dh = z.shape[1], z.shape[2]
        F = H * dh
        zq = transport_cast(z, fwd_dt)
        slab, n_slabs = _slab_layout(F, dh, zq.dtype.itemsize)
        z_pad = jnp.concatenate(
            [zq.reshape(R, F), jnp.zeros((1, F), zq.dtype)])
        slabs = _make_slabs(z_pad, slab, n_slabs)
        el_pad = jnp.concatenate(
            [el, jnp.full((1, H), -jnp.inf, jnp.float32)])
        er_pad = jnp.concatenate([er, jnp.zeros((1, H), jnp.float32)])

        outs, ms, ss = [], [], []
        for mat, rows in fwd:
            per = rows_per_chunk(mat.shape[1], F)
            mat_c, rows_c, n_b = _chunked(mat, rows, per, R, n_dst)

            def body(_, xs):
                idx, rr = xs
                lel = jnp.take(el_pad, idx, axis=0,
                               mode="clip")    # [r, D, H]
                l_pre = lel + jnp.take(er_pad, rr, axis=0,
                                       mode="clip")[:, None, :]
                l = _leaky(l_pre, slope)
                m = l.max(axis=1)                          # [r, H]
                m = jnp.where(jnp.isfinite(m), m, 0.0)     # all-pad rows
                w = jnp.exp(l - m[:, None, :])             # pads -> 0
                s = w.sum(axis=1)
                o = _gather_weighted(
                    slabs, idx, w, slab, dh,
                    jnp.zeros((idx.shape[0], H, dh), jnp.float32))
                return None, (o, m, s)

            _, (o, m, s) = jax.lax.scan(body, None, (mat_c, rows_c))
            outs.append(o.reshape(-1, H, dh)[:n_b])
            ms.append(m.reshape(-1, H)[:n_b])
            ss.append(s.reshape(-1, H)[:n_b])
        # sentinel row: out 0, s 1 (zero-in-degree rows emit 0, no 0/0)
        out_c = jnp.concatenate(outs + [jnp.zeros((1, H, dh),
                                                  jnp.float32)])
        m_c = jnp.concatenate(ms + [jnp.zeros((1, H), jnp.float32)])
        s_c = jnp.concatenate(ss + [jnp.ones((1, H), jnp.float32)])
        out = jnp.take(out_c, fwd_inv, axis=0, mode="clip")[:n_dst]
        m = jnp.take(m_c, fwd_inv, axis=0, mode="clip")[:n_dst]
        s = jnp.take(s_c, fwd_inv, axis=0, mode="clip")[:n_dst]
        return out / s[..., None], m, s

    @jax.custom_vjp
    def gat(z, el, er):
        return fwd_pass(z, el, er)[0]

    def gat_fwd(z, el, er):
        out, m, s = fwd_pass(z, el, er)
        return out, (z, el, er, out, m, s)

    def gat_bwd(res, g):
        z, el, er, out, m, s = res
        H, dh = z.shape[1], z.shape[2]
        F = H * dh
        g = g.astype(jnp.float32)
        rho = (g * out).sum(-1)                            # [n_dst, H]

        zq = transport_cast(z, fwd_dt)  # the SAME quantized values the
        # forward consumed — pass A's contractions then differentiate
        # the quantized forward exactly
        slab, n_slabs = _slab_layout(F, dh, zq.dtype.itemsize)
        z_pad = jnp.concatenate(
            [zq.reshape(R, F), jnp.zeros((1, F), zq.dtype)])
        z_slabs = _make_slabs(z_pad, slab, n_slabs)
        el_pad = jnp.concatenate(
            [el, jnp.full((1, H), -jnp.inf, jnp.float32)])
        er_pad = jnp.concatenate([er, jnp.zeros((1, H), jnp.float32)])

        # ---- pass A (dst-keyed): d_er ---------------------------------
        # alpha and dl recompute from (el gather, row-local m/s/rho);
        # the wide gather contracts z[src] against the row's cotangent
        ders = []
        for mat, rows in fwd:
            per = rows_per_chunk(mat.shape[1], F)
            mat_c, rows_c, n_b = _chunked(mat, rows, per, R, n_dst)

            def body_a(_, xs):
                idx, rr = xs
                lel = jnp.take(el_pad, idx, axis=0, mode="clip")
                err = jnp.take(er_pad, rr, axis=0,
                               mode="clip")      # [r, H]
                l_pre = lel + err[:, None, :]
                mr = jnp.take(m, jnp.minimum(rr, n_dst - 1), axis=0)
                sr = jnp.take(s, jnp.minimum(rr, n_dst - 1), axis=0)
                rhor = jnp.take(rho, jnp.minimum(rr, n_dst - 1), axis=0)
                alpha = jnp.exp(_leaky(l_pre, slope) - mr[:, None, :]) \
                    / sr[:, None, :]
                g_rows = jnp.take(
                    g, jnp.minimum(rr, n_dst - 1), axis=0
                ) * (rr < n_dst).astype(jnp.float32)[:, None, None]
                c = _gather_contract(z_slabs, idx, g_rows, slab, dh)
                dl = alpha * (c - rhor[:, None, :])
                return None, (dl * _dleaky(l_pre, slope)).sum(axis=1)

            _, der_b = jax.lax.scan(body_a, None, (mat_c, rows_c))
            ders.append(der_b.reshape(-1, H)[:n_b])
        der_c = jnp.concatenate(ders + [jnp.zeros((1, H), jnp.float32)])
        der = jnp.take(der_c, fwd_inv, axis=0, mode="clip")[:n_dst]

        # ---- pass B (src-keyed transpose): d_z, d_el ------------------
        # per-dst stats ride ONE narrow stacked gather; m sentinel +inf
        # zeroes pad-slot alphas
        stats = jnp.concatenate([er, m, s, rho], axis=1)   # [n_dst, 4H]
        stats_pad = jnp.concatenate([
            stats,
            jnp.concatenate([
                jnp.zeros((1, H)), jnp.full((1, H), jnp.inf),
                jnp.ones((1, H)), jnp.zeros((1, H))], axis=1
            ).astype(jnp.float32)])
        g_t = transport_cast(g, bwd_dt) if bwd_dt is not None \
            else g.astype(z.dtype)
        slab_g, n_slabs_g = _slab_layout(F, dh, g_t.dtype.itemsize)
        g_pad = jnp.concatenate(
            [g_t.reshape(n_dst, F), jnp.zeros((1, F), g_t.dtype)])
        g_slabs = _make_slabs(g_pad, slab_g, n_slabs_g)
        # rowvec z values must be the SAME quantized values the forward
        # consumed (zq), or pass B's dl = alpha*(c - rho) mixes
        # unquantized z against quantized-forward rho and biases d_el
        z_pad3 = jnp.concatenate([
            zq.astype(jnp.float32).reshape(R, H, dh),
            jnp.zeros((1, H, dh), jnp.float32)])

        dzs, dels = [], []
        for mat, rows in bwd:
            per = rows_per_chunk(mat.shape[1], F)
            mat_c, rows_c, n_b = _chunked(mat, rows, per, n_dst, R)

            def body_b(_, xs):
                idx, rr = xs
                st = jnp.take(stats_pad, idx, axis=0,
                              mode="clip")        # [r, D, 4H]
                er_g, m_g, s_g, rho_g = (
                    st[..., :H], st[..., H:2 * H],
                    st[..., 2 * H:3 * H], st[..., 3 * H:])
                el_r = jnp.take(el_pad, rr, axis=0,
                                mode="clip")        # [r, H]
                l_pre = el_r[:, None, :] + er_g
                alpha = jnp.exp(_leaky(l_pre, slope) - m_g) / s_g
                z_r = jnp.take(z_pad3, rr, axis=0,
                               mode="clip")         # [r, H, dh]
                dz_b, c = _gather_weighted_contract(
                    g_slabs, idx, alpha, z_r, slab_g, dh,
                    jnp.zeros((idx.shape[0], H, dh), jnp.float32))
                dl = alpha * (c - rho_g)
                del_b = (dl * _dleaky(l_pre, slope)).sum(axis=1)
                return None, (dz_b, del_b)

            _, (dz_b, del_b) = jax.lax.scan(body_b, None, (mat_c, rows_c))
            dzs.append(dz_b.reshape(-1, H, dh)[:n_b])
            dels.append(del_b.reshape(-1, H)[:n_b])
        dz_c = jnp.concatenate(dzs + [jnp.zeros((1, H, dh), jnp.float32)])
        del_c = jnp.concatenate(dels + [jnp.zeros((1, H), jnp.float32)])
        dz = jnp.take(dz_c, bwd_inv, axis=0, mode="clip")[:R].astype(z.dtype)
        d_el = jnp.take(del_c, bwd_inv, axis=0, mode="clip")[:R]
        return dz, d_el, der

    gat.defvjp(gat_fwd, gat_bwd)
    return gat
