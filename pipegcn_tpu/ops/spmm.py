"""Sparse message aggregation (SpMM) for TPU.

The TPU-native replacement for DGL's C++/CUDA `update_all(copy_src, sum)`
kernel (reference module/layer.py:47-49) — the hot op of every GraphSAGE
layer. Implemented as gather + segment-sum over a static-shaped edge list,
with an edge-chunked `lax.scan` so the gathered message tensor never
materializes at full [E, F] size (114M-edge graphs would need tens of GB
otherwise).

Conventions (produced by partition.halo.ShardedGraph):
  - `edge_dst` is sorted ascending per shard (CSR order) and padded with
    the sentinel `n_out`, whose segment row is dropped;
  - `edge_src` indexes into `fbuf` rows (inner nodes then halo slots);
    padded entries point at row 0 (harmless: their dst is the sentinel).

Table-driven kernels (ops/bucket_spmm.py, ops/block_spmm.py) swap in
behind the same signature via the trainer's spmm_fn closure.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _segment_sum_once(fbuf, edge_src, edge_dst, n_out, sorted_edges):
    # gather in fbuf's dtype (bf16 halves the random-row HBM traffic),
    # accumulate in f32 (bf16 sums over ~500-degree rows lose ~9 bits)
    msgs = jnp.take(fbuf, edge_src, axis=0,
                    mode="clip").astype(jnp.float32)
    return jax.ops.segment_sum(
        msgs, edge_dst, num_segments=n_out + 1,
        indices_are_sorted=sorted_edges,
    )[:n_out]


@partial(jax.jit, static_argnames=("n_out", "chunk", "sorted_edges"))
def spmm_sum(
    fbuf: jax.Array,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    n_out: int,
    chunk: Optional[int] = None,
    sorted_edges: bool = False,
) -> jax.Array:
    """Sum messages fbuf[edge_src] into rows edge_dst; output [n_out, F].

    `chunk` bounds the materialized message tensor to [chunk, F]; edges
    beyond a multiple of `chunk` are processed in a remainder step. When
    `chunk` is None or >= E, a single gather+segment-sum is used.

    `sorted_edges=True` promises edge_dst is ascending (the CSR order
    ShardedGraph emits) and lowers to the cheaper sorted-segment
    reduction. Chunks of a sorted list are sorted, so it composes with
    `chunk`.
    """
    e = edge_src.shape[0]
    if chunk is None or chunk >= e:
        return _segment_sum_once(fbuf, edge_src, edge_dst, n_out,
                                 sorted_edges)

    n_full = e // chunk
    main_src = edge_src[: n_full * chunk].reshape(n_full, chunk)
    main_dst = edge_dst[: n_full * chunk].reshape(n_full, chunk)

    def _chunk_sum(s, d):
        msgs = jnp.take(fbuf, s, axis=0, mode="clip").astype(jnp.float32)
        return jax.ops.segment_sum(
            msgs, d, num_segments=n_out + 1,
            indices_are_sorted=sorted_edges,
        )

    def body(acc, sd):
        return acc + _chunk_sum(*sd), None

    # seed the scan carry with the first chunk (not zeros) so the carry
    # inherits fbuf's varying-over-mesh type inside shard_map
    acc0 = _chunk_sum(main_src[0], main_dst[0])
    acc, _ = jax.lax.scan(body, acc0, (main_src[1:], main_dst[1:]))
    rem = e - n_full * chunk
    if rem:
        msgs = jnp.take(
            fbuf, edge_src[n_full * chunk :], axis=0, mode="clip"
        ).astype(jnp.float32)
        acc = acc + jax.ops.segment_sum(
            msgs, edge_dst[n_full * chunk :], num_segments=n_out + 1,
            indices_are_sorted=sorted_edges,
        )
    return acc[:n_out]


def spmm_mean(
    fbuf: jax.Array,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    in_deg: jax.Array,
    n_out: int,
    chunk: Optional[int] = None,
    sorted_edges: bool = False,
) -> jax.Array:
    """Mean aggregation: sum divided by precomputed in-degrees; always
    returns f32 (accumulation dtype) regardless of fbuf's dtype.

    The divisor is the in-degree of the *full* training graph, not the
    local shard (reference semantics: helper/utils.py:142 degrees are
    stored before partitioning and used at module/layer.py:47-50).

    For bf16 fbuf a custom VJP keeps the backward scatter-accumulation
    in f32 (autodiff through the cast would otherwise accumulate halo
    gradients in bf16, losing ~log2(degree) bits), casting the final
    d_fbuf back to bf16 once.
    """
    if fbuf.dtype == jnp.float32:
        s = spmm_sum(fbuf, edge_src, edge_dst, n_out, chunk, sorted_edges)
        return s / in_deg[:, None]
    return _spmm_mean_lowp(fbuf, edge_src, edge_dst, in_deg, n_out, chunk,
                           sorted_edges)


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _spmm_mean_lowp(fbuf, edge_src, edge_dst, in_deg, n_out, chunk,
                    sorted_edges):
    s = spmm_sum(fbuf, edge_src, edge_dst, n_out, chunk, sorted_edges)
    return s / in_deg[:, None]


def _spmm_mean_lowp_fwd(fbuf, edge_src, edge_dst, in_deg, n_out, chunk,
                        sorted_edges):
    out = _spmm_mean_lowp(fbuf, edge_src, edge_dst, in_deg, n_out, chunk,
                          sorted_edges)
    # zero-size proto carries fbuf's (static) row count and dtype through
    # the residuals, which must be JAX types. `out` rides along for the
    # in_deg cotangent; it is also the input of the layer's following
    # matmul, whose weight grad retains it anyway, so this adds no memory
    proto = jnp.zeros((fbuf.shape[0], 0), fbuf.dtype)
    return out, (edge_src, edge_dst, in_deg, proto, out)


def _spmm_mean_lowp_bwd(n_out, chunk, sorted_edges, res, g):
    edge_src, edge_dst, in_deg, proto, out = res
    n_rows, dt = proto.shape[0], proto.dtype
    gf = g.astype(jnp.float32)
    gd = gf / in_deg[:, None]
    # pad one sentinel row so pad edges (dst == n_out) read zeros; the
    # transpose aggregation is spmm_sum with edge roles swapped (f32
    # accumulation; pad edges then scatter harmless zeros into row 0,
    # their src under the module convention)
    gd = jnp.concatenate([gd, jnp.zeros((1, gd.shape[-1]), jnp.float32)])
    d_fbuf = spmm_sum(gd, edge_dst, edge_src, n_rows, chunk,
                      sorted_edges=False)
    # d(s/deg)/d(deg) = -s/deg^2 = -out/deg, contracted over features —
    # the f32 path autodiffs this; the two paths must agree (degrees are
    # normally data, but differentiating through them must not silently
    # yield zeros)
    d_in_deg = -jnp.sum(out.astype(jnp.float32) * gf, axis=-1) / in_deg
    ft0 = jax.dtypes.float0
    zint = lambda a: np.zeros(a.shape, ft0)
    return (d_fbuf.astype(dt), zint(edge_src), zint(edge_dst),
            d_in_deg.astype(in_deg.dtype))


_spmm_mean_lowp.defvjp(_spmm_mean_lowp_fwd, _spmm_mean_lowp_bwd)
