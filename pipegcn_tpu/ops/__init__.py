from .spmm import spmm_sum, spmm_mean

__all__ = ["spmm_sum", "spmm_mean"]
