from .partitioner import partition_graph
from .halo import ShardedGraph

__all__ = ["partition_graph", "ShardedGraph"]
