from .partitioner import locality_clusters, partition_graph
from .halo import ShardedGraph

__all__ = ["locality_clusters", "partition_graph", "ShardedGraph"]
