"""Halo index pipeline: partitioned graph -> static-shaped device arrays.

This is the TPU-native replacement for the reference's entire per-rank
graph-construction stack — boundary discovery (helper/utils.py:154-188),
halo ordering + renumbering (train.py:84-131, 206-229), train-first
permutation (train.py:134-155), and recv-shape computation
(train.py:101-110) — done once on host in numpy, producing arrays whose
shapes are identical on every device so a single SPMD program can be
traced over them.

Layout per device r (P devices total):

  rows [0, N_max)           : inner (owned) nodes, train nodes first
                              (local ids of train nodes are [0, n_train_r)),
                              padded with zero rows up to N_max
  rows [N_max + (d-1)*B_max + k) for d in 1..P-1, k in [0, B_max):
                              halo slot k of ring distance d — after the
                              exchange step at distance d it holds entry k
                              of the send list of owner q = (r-d) mod P

The send list S[r][d-1] contains local indices of r's inner nodes needed
by the peer t = (r+d) mod P (nodes with an out-edge into t), sorted by
local id, padded to B_max. Keying halo blocks by ring *distance* instead
of owner rank (the reference sorts by owner rank, train.py:120-131) makes
the ppermute-based exchange's recv offsets identical across devices —
the property that lets one traced program serve all shards.

Local edges: every global edge (u, v) with part(v) == r appears exactly
once on device r as (src_local, dst_local); src_local is an inner id or a
halo slot. Edge arrays are padded to E_max with (src=0, dst=N_max); the
dst sentinel routes padded contributions into a dropped segment.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional

import numpy as np

from ..graph.csr import Graph


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m if m > 0 else x


# host-side edge-pass chunk: bounds O(E) int64 temporaries during
# checksums and the chunked build (6-7 per-edge int64 scratch arrays at
# a time -> ~0.9 GB per 16M-edge chunk instead of all-E at once)
_EDGE_CHUNK = 16 * 1024 * 1024


def _stable_argsort(keys: np.ndarray) -> np.ndarray:
    """Stable argsort of non-negative int64 fused keys — the difference
    between seconds and minutes at 114M edges. Thin alias of
    native.stable_argsort (kept for this module's call sites)."""
    from ..native import stable_argsort

    return stable_argsort(keys)


class _RaggedEdges:
    """Per-rank trimmed edge arrays of a trim_edges v3 artifact.

    Indexing by rank (`arr[r]`) memmaps that rank's trimmed 1-D file —
    slicing it `[:edge_count[r]]` is the identity, so per-rank code
    written against the padded [P, e_max] stack works unchanged.
    Whole-array operations (astype/reshape/...) are intentionally
    unsupported: the padded stack was not stored."""

    def __init__(self, adir: str, key: str, num_parts: int):
        self._adir = adir
        self._key = key
        self.num_parts = num_parts

    def __len__(self):
        return self.num_parts

    def __getitem__(self, r):
        if not isinstance(r, (int, np.integer)):
            raise TypeError(
                f"{self._key} is stored per-rank trimmed "
                "(trim_edges artifact); index by rank int only")
        if not 0 <= int(r) < self.num_parts:
            raise IndexError(
                f"rank {r} out of range [0, {self.num_parts})")
        return np.load(os.path.join(self._adir,
                                    f"{self._key}_r{int(r):03d}.npy"),
                       mmap_mode="r")

    def __array__(self, *a, **kw):
        # numpy coercion (np.asarray / zeros_like / iteration fallback)
        # must fail with the explanatory message, not a confusing
        # FileNotFoundError past the last rank or a silently unpadded
        # stack of equal-length ranks
        raise TypeError(
            f"{self._key} is a trim_edges per-rank view; the padded "
            "[P, e_max] stack was not stored — re-save without "
            "trim_edges for whole-array consumers")

    def __getattr__(self, name):
        raise AttributeError(
            f"{self._key} is a trim_edges per-rank view; the padded "
            f"[P, e_max] stack was not stored (re-save without "
            f"trim_edges for whole-array consumers like the mesh "
            f"Trainer) — attribute {name!r} unsupported")


@dataclasses.dataclass
class ShardedGraph:
    """Stacked per-device arrays (leading axis = device / partition).

    All integer index arrays are int32 (TPU-friendly); features float32.
    """

    num_parts: int
    n_max: int          # padded inner-node rows per device
    b_max: int          # padded send-list length (per peer distance)
    e_max: int          # padded edge count per device
    n_train_global: int
    n_feat: int
    n_class: int
    multilabel: bool

    inner_count: np.ndarray   # [P] real inner nodes per device
    train_count: np.ndarray   # [P] train nodes per device (local ids [0, t))
    edge_count: np.ndarray    # [P] real edges per device
    send_counts: np.ndarray   # [P, P-1] real send-list lengths

    edge_src: np.ndarray      # [P, E_max] int32 in [0, N_max + (P-1)*B_max)
    edge_dst: np.ndarray      # [P, E_max] int32 in [0, N_max]; N_max = pad
    send_idx: np.ndarray      # [P, P-1, B_max] int32 local inner ids
    send_mask: np.ndarray     # [P, P-1, B_max] bool

    feat: np.ndarray          # [P, N_max, F]
    label: np.ndarray         # [P, N_max] int64 or [P, N_max, C] float32
    train_mask: np.ndarray    # [P, N_max] bool (padding rows False)
    val_mask: np.ndarray      # [P, N_max] bool
    test_mask: np.ndarray     # [P, N_max] bool
    in_deg: np.ndarray        # [P, N_max] float32 (padding rows 1.0)
    global_nid: np.ndarray    # [P, N_max] int64 (padding rows -1)

    # wraparound-uint64 checksum of the source graph's global edge list
    # (identifies "is this sharded graph built from exactly graph g?" —
    # node-ID cover alone can't distinguish graphs sharing a node set);
    # -1 in artifacts saved before the field existed
    source_edge_checksum: int = -1

    # locality reorder layout (partitioner.REORDER_MODES): which node
    # renumbering this artifact's local ids follow. Pre-reorder
    # artifacts default to "none"/layout v1 on load; new builds stamp
    # LAYOUT_VERSION. reorder_perm[p, l] is the local id node (p, l)
    # would have under reorder="none" (the base layout), reorder_inv
    # its inverse; -1 on padding rows, None when reorder == "none".
    reorder: str = "none"
    layout_version: int = 1
    reorder_perm: Optional[np.ndarray] = None
    reorder_inv: Optional[np.ndarray] = None

    # set by load(): the artifact directory, which doubles as the cache
    # location for derived per-device kernel tables (bucket/block) so
    # repeat runs skip their O(E) host builds. Not serialized.
    cache_dir: Optional[str] = None

    # set by load(parts=...): the global partition ids THIS process
    # will own under the current elastic membership assignment
    # (resilience/elastic.py); None = unsupervised / owns everything.
    # Not serialized — the assignment is a property of the run, the
    # artifact stays world-size independent.
    local_parts: Optional[tuple] = None

    @property
    def halo_size(self) -> int:
        return (self.num_parts - 1) * self.b_max

    @staticmethod
    def edge_checksum(g: Graph) -> int:
        # splitmix64-mix each fused (src, dst) pair BEFORE the order-free
        # sum: a plain sum of src*N + dst is linear (N*Σsrc + Σdst) and
        # collides for any re-pairing of the same endpoints — exactly the
        # rewired-graph case the checksum must detect. Chunked so the
        # uint64 temporaries stay bounded at papers100M scale (the sum
        # is order-free, so chunking cannot change the result).
        total = 0
        nn = np.uint64(g.num_nodes)
        for i0 in range(0, g.num_edges, _EDGE_CHUNK):
            sl = slice(i0, min(i0 + _EDGE_CHUNK, g.num_edges))
            x = g.src[sl].astype(np.uint64) * nn \
                + g.dst[sl].astype(np.uint64)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            x ^= x >> np.uint64(31)
            # explicit mod-2^64 accumulation (a np.uint64 scalar add
            # wraps identically but emits RuntimeWarning per chunk)
            total = (total + int(x.sum(dtype=np.uint64))) & ((1 << 64) - 1)
        return total

    # ------------------------------------------------------------------
    @staticmethod
    def _padded_dim(raw: int, pad_to: int, slack: float = 0.0,
                    floor: int = 0) -> int:
        """Padded size of a per-device dimension: the raw maximum grown
        by the streaming `slack` fraction (reserved headroom so
        stream/patch.py can add entries without changing compiled
        shapes), floored at `floor` (a bit-identity re-pad target), then
        rounded up to `pad_to`."""
        grown = int(np.ceil(raw * (1.0 + max(slack, 0.0))))
        return _round_up(max(grown, int(floor)), pad_to)

    @staticmethod
    def _send_structures(pair_fused: np.ndarray, parts: np.ndarray,
                         local_id: np.ndarray, num_parts: int, n: int,
                         pad_to: int, slack: float = 0.0,
                         min_b_max: int = 0) -> Dict[str, np.ndarray]:
        """Send lists + halo-slot lookup from the sorted unique
        (node, dest part) fused-pair array — the shared core of build()
        and build_chunked().

        Returns send_counts/b_max/send_idx/send_mask plus the pair->slot
        lookup pieces (`fused_sorted` = pair_fused itself, `dist`,
        `rank_in_group`, `order` = inverse of the send-list sort) used
        to localize cross-edge sources."""
        p_node = pair_fused // num_parts
        p_dest = (pair_fused % num_parts).astype(np.int32)
        p_owner = parts[p_node]
        # sort by (owner, dest, local id) -> grouped send lists in order
        skey = _stable_argsort(
            (p_owner.astype(np.int64) * num_parts + p_dest) * n
            + local_id[p_node]
        )
        p_node, p_dest, p_owner = p_node[skey], p_dest[skey], p_owner[skey]

        # group starts for each (owner, dest) combination
        combo = p_owner.astype(np.int64) * num_parts + p_dest
        send_counts = np.bincount(
            combo, minlength=num_parts * num_parts
        ).reshape(num_parts, num_parts)
        assert np.all(np.diag(send_counts) == 0)
        b_max = ShardedGraph._padded_dim(
            int(send_counts.max()), pad_to, slack, min_b_max
        ) if num_parts > 1 else 0

        combo_starts = np.zeros(num_parts * num_parts + 1, dtype=np.int64)
        np.cumsum(send_counts.reshape(-1), out=combo_starts[1:])
        rank_in_group = np.arange(p_node.shape[0]) - combo_starts[combo]

        # send_idx[r, d-1, k] = local id of k-th node r sends to (r+d)%P
        # (empty index arrays make these assignments no-ops, so the exact
        # shape works for P == 1 and b_max == 0 too)
        send_idx = np.zeros((num_parts, num_parts - 1, b_max),
                            dtype=np.int32)
        send_mask = np.zeros_like(send_idx, dtype=bool)
        dist = (p_dest - p_owner) % num_parts  # ring distance in 1..P-1
        send_idx[p_owner, dist - 1, rank_in_group] = \
            local_id[p_node].astype(np.int32)
        send_mask[p_owner, dist - 1, rank_in_group] = True

        # pair -> slot lookup via a dict-free merge: pair_fused is
        # already sorted by (node, dest) and p_* are its skey-
        # permutation, so the sorted key array IS pair_fused and the
        # sort order is skey's inverse — no third large sort needed
        fused_sorted_order = np.empty_like(skey)
        fused_sorted_order[skey] = np.arange(skey.size)
        return {
            "send_counts": send_counts,
            "b_max": b_max,
            "send_idx": send_idx,
            "send_mask": send_mask,
            "fused_sorted": pair_fused,
            # rank/dist in pair_fused order (hoisted out of the per-
            # chunk edge localization)
            "rank_by_pair": rank_in_group[fused_sorted_order],
            "dist_by_pair": dist[fused_sorted_order],
        }

    @staticmethod
    def _localize_edges(src: np.ndarray, dst: np.ndarray,
                        parts: np.ndarray, local_id: np.ndarray,
                        ss: Dict[str, np.ndarray], num_parts: int,
                        n_max: int, b_max: int):
        """(src_local, dst_local) int64 for a slice of global edges: an
        inner source maps to its local id, a cross source to its halo
        slot n_max + (dist-1)*b_max + rank in the owner's send list."""
        fused_sorted = ss["fused_sorted"]
        dst_local = local_id[dst].astype(np.int64)
        src_inner = parts[src] == parts[dst]
        edge_fused = src.astype(np.int64) * num_parts + parts[dst]
        loc = np.searchsorted(fused_sorted, edge_fused)
        # (only valid where cross; guard indices)
        loc = np.clip(loc, 0, max(fused_sorted.size - 1, 0))
        if fused_sorted.size:
            halo_rank = ss["rank_by_pair"][loc]
            halo_dist = ss["dist_by_pair"][loc]
        else:
            halo_rank = np.zeros_like(edge_fused)
            halo_dist = np.ones_like(edge_fused)
        src_local = np.where(
            src_inner,
            local_id[src],
            n_max + (halo_dist - 1) * b_max + halo_rank,
        ).astype(np.int64)
        return src_local, dst_local

    # ------------------------------------------------------------------
    @staticmethod
    def _local_ids(n: int, train_mask: np.ndarray, parts: np.ndarray,
                   num_parts: int, cluster: Optional[np.ndarray],
                   rkey: Optional[np.ndarray]):
        """Local-id assignment: sort nodes by (part, ~is_train
        [, reorder key][, cluster], global id) into contiguous per-part
        train-first blocks. Returns (local_id, part_sizes)."""
        keys = [np.arange(n)]
        if cluster is not None:
            keys.append(cluster.astype(np.int64))
        if rkey is not None:
            keys.append(np.asarray(rkey, dtype=np.int64))
        keys += [~train_mask, parts]
        order = np.lexsort(tuple(keys))
        part_sizes = np.bincount(parts, minlength=num_parts)
        part_starts = np.zeros(num_parts + 1, dtype=np.int64)
        np.cumsum(part_sizes, out=part_starts[1:])
        local_id = np.empty(n, dtype=np.int64)
        local_id[order] = np.arange(n) - part_starts[parts[order]]
        return local_id, part_sizes

    @staticmethod
    def _reorder_arrays(g: Graph, reorder: str, train_mask, parts,
                        num_parts, cluster, local_id, n_max):
        """(rkey-resolved reorder tag, perm, inv) for a build. The perm
        maps the reordered layout back to the base (reorder='none')
        layout so external consumers can translate local ids either
        way; both are [P, n_max] int32, -1 on padding rows."""
        if reorder in (None, "none"):
            return "none", None, None
        base_lid, _ = ShardedGraph._local_ids(
            g.num_nodes, train_mask, parts, num_parts, cluster, None)
        perm = np.full((num_parts, n_max), -1, np.int32)
        inv = np.full((num_parts, n_max), -1, np.int32)
        perm[parts, local_id] = base_lid.astype(np.int32)
        inv[parts, base_lid] = local_id.astype(np.int32)
        return reorder, perm, inv

    @staticmethod
    def build(
        g: Graph,
        parts: np.ndarray,
        n_parts: Optional[int] = None,
        pad_to: int = 8,
        cluster: Optional[np.ndarray] = None,
        reorder: str = "none",
        reorder_seed: int = 0,
        slack: float = 0.0,
        min_n_max: int = 0,
        min_b_max: int = 0,
        min_e_max: int = 0,
    ) -> "ShardedGraph":
        """Build the sharded layout from a graph and a partition assignment.

        `g` must be finalized (self loops + in_deg). `parts` is [N] int.
        `n_parts` is the intended device count; defaults to parts.max()+1
        but must be passed explicitly when trailing partitions could be
        empty (an empty shard is valid, just wasteful).

        `cluster` ([N] int, optional) adds a locality key to the local
        renumbering: within each partition's train and non-train
        segments, nodes sort by (cluster, global id) instead of global id
        alone, so community members get contiguous local ids and the
        shard adjacency concentrates into dense tiles (what
        ops/block_spmm.py exploits). Purely an ordering choice — every
        layout invariant (train-first, CSR edges, send lists) holds for
        any consistent order.

        `reorder` (partitioner.REORDER_MODES) adds the locality
        renumbering key BELOW the train segment and ABOVE the cluster
        key: within each partition's train/non-train segments inner
        nodes follow degree-bucket-major, BFS-locality-minor order so
        the SpMM gather index streams collapse into contiguous runs
        (ops/bucket_spmm slab plans). The base-layout permutation and
        its inverse are stored on the result (reorder_perm/reorder_inv)
        and ride the artifact.

        `slack` (streaming headroom, stream/patch.py) grows every padded
        per-device dimension (n_max, b_max, e_max) by that fraction over
        its raw maximum before rounding, reserving in-place growth room
        for delta patching without changing compiled shapes. The
        `min_*` floors force specific padded dimensions — the re-pad
        path and the patched-vs-rebuilt bit-identity oracle use them to
        rebuild a graph into the exact layout a patched ShardedGraph
        occupies.
        """
        n = g.num_nodes
        parts = parts.astype(np.int32)
        num_parts = int(n_parts) if n_parts is not None else int(parts.max()) + 1
        if num_parts < int(parts.max()) + 1:
            raise ValueError(
                f"n_parts={num_parts} smaller than max partition id "
                f"{int(parts.max())}"
            )
        train_mask = g.ndata["train_mask"]

        # ---- local ids: train-first within each partition ------------
        from .partitioner import reorder_key

        rkey = reorder_key(g, reorder, seed=reorder_seed)
        local_id, part_sizes = ShardedGraph._local_ids(
            n, train_mask, parts, num_parts, cluster, rkey)

        inner_count = part_sizes.astype(np.int32)
        train_count = np.bincount(
            parts[train_mask], minlength=num_parts
        ).astype(np.int32)

        n_max = ShardedGraph._padded_dim(
            int(part_sizes.max()), pad_to, slack, min_n_max)

        # ---- send lists ----------------------------------------------
        # cross edges define which (owner node, dest part) pairs exist;
        # fusing (node, dest) into one key makes the unique a cheap 1-D
        # sort instead of numpy's slow axis-0 row unique
        cross = parts[g.src] != parts[g.dst]
        cs, cd = g.src[cross], g.dst[cross]
        pair_fused = np.unique(
            cs.astype(np.int64) * num_parts + parts[cd]
        )  # sorted by (node, dest part), same order as the row unique
        ss = ShardedGraph._send_structures(pair_fused, parts, local_id,
                                           num_parts, n, pad_to,
                                           slack=slack,
                                           min_b_max=min_b_max)
        send_counts, b_max = ss["send_counts"], ss["b_max"]
        send_idx, send_mask = ss["send_idx"], ss["send_mask"]

        # ---- per-device edges ----------------------------------------
        edge_owner = parts[g.dst]  # device that owns each edge
        e_sizes = np.bincount(edge_owner, minlength=num_parts)
        e_max = ShardedGraph._padded_dim(
            int(e_sizes.max()), 128, slack, min_e_max)

        src_local_all, dst_local_all = ShardedGraph._localize_edges(
            g.src, g.dst, parts, local_id, ss, num_parts, n_max, b_max)

        # scatter edges into per-device padded arrays, sorted by local dst
        # within each device (CSR order — lets kernels rely on contiguous
        # destination segments; padding dst = n_max sorts to the tail)
        # THE hot host sort (E entries); fused single key + radix sort
        e_order = _stable_argsort(
            edge_owner.astype(np.int64) * (n_max + 1) + dst_local_all
        )
        e_starts = np.zeros(num_parts + 1, dtype=np.int64)
        np.cumsum(e_sizes, out=e_starts[1:])
        edge_src = np.zeros((num_parts, e_max), dtype=np.int32)
        edge_dst = np.full((num_parts, e_max), n_max, dtype=np.int32)
        pos_in_dev = np.arange(g.num_edges) - e_starts[edge_owner[e_order]]
        edge_src[edge_owner[e_order], pos_in_dev] = src_local_all[e_order]
        edge_dst[edge_owner[e_order], pos_in_dev] = dst_local_all[e_order]

        reo = ShardedGraph._reorder_arrays(
            g, reorder, train_mask, parts, num_parts, cluster,
            local_id, n_max)
        return ShardedGraph._assemble(
            g, parts, local_id, num_parts, n_max, b_max, e_max,
            e_sizes, inner_count, train_count, send_counts,
            edge_src, edge_dst, send_idx, send_mask, reorder=reo,
        )

    @staticmethod
    def _assemble(g, parts, local_id, num_parts, n_max, b_max, e_max,
                  e_sizes, inner_count, train_count, send_counts,
                  edge_src, edge_dst, send_idx, send_mask,
                  node_chunk: Optional[int] = None,
                  reorder=("none", None, None)) -> "ShardedGraph":
        """Per-device node-data scatter + dataclass construction — shared
        tail of build() and build_chunked(). `node_chunk` streams the
        feature scatter in row slices so a memmapped g.ndata['feat'] is
        never materialized whole."""
        n = g.num_nodes
        train_mask = np.asarray(g.ndata["train_mask"])

        def scatter_nodes(x: np.ndarray, fill) -> np.ndarray:
            shape = (num_parts, n_max) + x.shape[1:]
            out = np.full(shape, fill, dtype=x.dtype)
            out[parts, local_id] = x
            return out

        fsrc = g.ndata["feat"]
        if node_chunk:
            feat = np.zeros((num_parts, n_max) + fsrc.shape[1:],
                            np.float32)
            for i0 in range(0, n, node_chunk):
                sl = slice(i0, min(i0 + node_chunk, n))
                feat[parts[sl], local_id[sl]] = \
                    np.asarray(fsrc[sl], dtype=np.float32)
        else:
            feat = scatter_nodes(np.asarray(fsrc, np.float32), 0.0)
        label_arr = np.asarray(g.ndata["label"])
        multilabel = label_arr.ndim == 2
        if multilabel:
            label = scatter_nodes(label_arr.astype(np.float32), 0.0)
            n_class = int(label_arr.shape[1])
        else:
            label = scatter_nodes(label_arr.astype(np.int64), 0)
            n_class = int(label_arr.max()) + 1
        tm = scatter_nodes(train_mask.astype(bool), False)
        vm = scatter_nodes(
            np.asarray(g.ndata.get("val_mask", np.zeros(n, bool)),
                       bool), False
        )
        sm = scatter_nodes(
            np.asarray(g.ndata.get("test_mask", np.zeros(n, bool)),
                       bool), False
        )
        # degrees of the graph being partitioned (reference utils.py:142);
        # finalize()/node_subgraph keep ndata['in_deg'] consistent with the
        # attached graph, so prefer it over an O(E) recompute
        deg = g.ndata.get("in_deg")
        if deg is None:
            deg = g.in_degrees()
        in_deg = scatter_nodes(np.asarray(deg, np.float32), 1.0)
        in_deg[in_deg == 0] = 1.0
        gnid = scatter_nodes(np.arange(n, dtype=np.int64), -1)

        return ShardedGraph(
            num_parts=num_parts,
            n_max=n_max,
            b_max=b_max,
            e_max=e_max,
            n_train_global=int(train_mask.sum()),
            n_feat=int(feat.shape[-1]),
            n_class=n_class,
            multilabel=multilabel,
            inner_count=inner_count,
            train_count=train_count,
            edge_count=e_sizes.astype(np.int32),
            send_counts=send_counts[
                np.arange(num_parts)[:, None],
                (np.arange(num_parts)[:, None] + np.arange(1, max(num_parts, 2)))
                % num_parts,
            ].astype(np.int32) if num_parts > 1 else np.zeros((1, 0), np.int32),
            edge_src=edge_src,
            edge_dst=edge_dst,
            send_idx=send_idx,
            send_mask=send_mask,
            feat=feat,
            label=label,
            train_mask=tm,
            val_mask=vm,
            test_mask=sm,
            in_deg=in_deg,
            global_nid=gnid,
            source_edge_checksum=ShardedGraph.edge_checksum(g),
            reorder=reorder[0],
            # reorder="none" IS the v1 layout bit-for-bit: keep version 1
            # so existing tuning tables stay signature-valid for it
            layout_version=(ShardedGraph.LAYOUT_VERSION
                            if reorder[0] != "none" else 1),
            reorder_perm=reorder[1],
            reorder_inv=reorder[2],
        )

    # ------------------------------------------------------------------
    @staticmethod
    def build_chunked(
        g: Graph,
        parts: np.ndarray,
        n_parts: Optional[int] = None,
        pad_to: int = 8,
        cluster: Optional[np.ndarray] = None,
        reorder: str = "none",
        reorder_seed: int = 0,
        edge_chunk: int = _EDGE_CHUNK,
        node_chunk: int = 1 << 20,
    ) -> "ShardedGraph":
        """RAM-bounded build for papers100M-class graphs: bit-identical
        output to build(), with every O(E) pass chunked.

        build() materializes ~7 per-edge int64 scratch arrays at once
        (~180 GB at papers100M's 3.2B post-mirror edges — the regime the
        reference handles with a >=120 GB-RAM host, reference
        README.md:29-30); here the peak transient is O(edge_chunk) for
        the edge passes + one per-device argsort (E/P), so the resident
        set is dominated by the artifact itself. g.src/g.dst/g.ndata may
        be memmaps — every access is sliced.

        Equality with build() holds exactly: chunks preserve arrival
        order per device, and the final per-device stable dst sort
        reproduces build()'s global stable (owner, dst) order.
        """
        n = g.num_nodes
        parts = parts.astype(np.int32)
        num_parts = int(n_parts) if n_parts is not None \
            else int(parts.max()) + 1
        if num_parts < int(parts.max()) + 1:
            raise ValueError(
                f"n_parts={num_parts} smaller than max partition id "
                f"{int(parts.max())}"
            )
        train_mask = np.asarray(g.ndata["train_mask"])

        # ---- local ids (O(N), same as build) --------------------------
        from .partitioner import reorder_key

        rkey = reorder_key(g, reorder, seed=reorder_seed)
        local_id, part_sizes = ShardedGraph._local_ids(
            n, train_mask, parts, num_parts, cluster, rkey)
        inner_count = part_sizes.astype(np.int32)
        train_count = np.bincount(
            parts[train_mask], minlength=num_parts
        ).astype(np.int32)
        n_max = _round_up(int(part_sizes.max()), pad_to)

        # ---- pass 1 (chunked): owner counts + cross-pair uniques ------
        E = g.num_edges
        e_sizes = np.zeros(num_parts, np.int64)
        pair_chunks = []
        for i0 in range(0, E, edge_chunk):
            sl = slice(i0, min(i0 + edge_chunk, E))
            s = np.asarray(g.src[sl])
            d = np.asarray(g.dst[sl])
            pd = parts[d]
            e_sizes += np.bincount(pd, minlength=num_parts)
            cross = parts[s] != pd
            pair_chunks.append(np.unique(
                s[cross].astype(np.int64) * num_parts + pd[cross]))
        pair_fused = np.unique(np.concatenate(pair_chunks)) \
            if pair_chunks else np.zeros(0, np.int64)
        ss = ShardedGraph._send_structures(pair_fused, parts, local_id,
                                           num_parts, n, pad_to)
        send_counts, b_max = ss["send_counts"], ss["b_max"]
        e_max = _round_up(int(e_sizes.max()), 128)

        # ---- pass 2 (chunked): localize + scatter in arrival order ----
        edge_src = np.zeros((num_parts, e_max), dtype=np.int32)
        edge_dst = np.full((num_parts, e_max), n_max, dtype=np.int32)
        cursor = np.zeros(num_parts, np.int64)
        for i0 in range(0, E, edge_chunk):
            sl = slice(i0, min(i0 + edge_chunk, E))
            s = np.asarray(g.src[sl])
            d = np.asarray(g.dst[sl])
            src_l, dst_l = ShardedGraph._localize_edges(
                s, d, parts, local_id, ss, num_parts, n_max, b_max)
            owner = parts[d]
            o = _stable_argsort(owner.astype(np.int64))
            ow = owner[o]
            cnt = np.bincount(ow, minlength=num_parts)
            starts = np.zeros(num_parts + 1, np.int64)
            np.cumsum(cnt, out=starts[1:])
            pos = cursor[ow] + (np.arange(ow.size) - starts[ow])
            edge_src[ow, pos] = src_l[o]
            edge_dst[ow, pos] = dst_l[o]
            cursor += cnt

        # ---- per-device CSR sort (stable by local dst) ----------------
        for r in range(num_parts):
            e_r = int(e_sizes[r])
            if not e_r:
                continue
            o = _stable_argsort(edge_dst[r, :e_r].astype(np.int64))
            edge_src[r, :e_r] = edge_src[r, :e_r][o]
            edge_dst[r, :e_r] = edge_dst[r, :e_r][o]

        reo = ShardedGraph._reorder_arrays(
            g, reorder, train_mask, parts, num_parts, cluster,
            local_id, n_max)
        return ShardedGraph._assemble(
            g, parts, local_id, num_parts, n_max, b_max, e_max,
            e_sizes, inner_count, train_count, send_counts,
            edge_src, edge_dst, ss["send_idx"], ss["send_mask"],
            node_chunk=node_chunk, reorder=reo,
        )

    # ------------------------------------------------------------------
    # Partition artifact on disk (reference: dgl partition JSON + per-part
    # files, helper/utils.py:132-144 / 99-129; enables --skip-partition).

    _ARRAYS = [
        "inner_count", "train_count", "edge_count", "send_counts",
        "edge_src", "edge_dst", "send_idx", "send_mask", "feat", "label",
        "train_mask", "val_mask", "test_mask", "in_deg", "global_nid",
    ]

    # format history: v1 edges grouped by device only; v2 adds the per-
    # device dst-sorted (CSR) edge order that spmm's sorted path relies
    # on; v3 stores the same arrays as individual uncompressed .npy
    # files so loaders can mmap them (papers100M-class artifacts exceed
    # RAM as one decompressed npz; a v3 reader touches only the ranks
    # it slices — the per-rank loading the reference gets from dgl's
    # per-part files, helper/utils.py:132-144)
    FORMAT_VERSION = 2
    MMAP_FORMAT_VERSION = 3

    # layout contract version (orthogonal to the storage format above):
    # v1 = pre-reorder local-id contract; v2 = reorder-aware — the
    # manifest carries the reorder tag and, when reorder != "none", the
    # permutation arrays. v1 artifacts load as reorder="none".
    LAYOUT_VERSION = 2
    _REORDER_ARRAYS = ["reorder_perm", "reorder_inv"]

    def save(self, path: str, mmap: bool = False,
             trim_edges: bool = False) -> None:
        """trim_edges (v3/mmap only): store edge_src/edge_dst per rank,
        TRIMMED to each rank's real edge count, instead of the padded
        [P, e_max] stack — at papers100M scale the pareto-hub rank sets
        e_max ~2.7x the mean and the padded stack alone is ~69 GB on
        disk. load() then returns a _RaggedEdges view for those two
        keys; per-rank consumers (SequentialRunner, the ladder scan)
        index it exactly like the stacked array (`arr[r][:e]`), while
        whole-array consumers fail loudly (the mesh Trainer wants the
        padded stack — rebuild without trim_edges for that)."""
        if trim_edges and not mmap:
            raise ValueError("trim_edges requires mmap=True (v3)")
        os.makedirs(path, exist_ok=True)
        manifest = {
            "format_version": (self.MMAP_FORMAT_VERSION if mmap
                               else self.FORMAT_VERSION),
            "num_parts": self.num_parts,
            "n_max": self.n_max,
            "b_max": self.b_max,
            "e_max": self.e_max,
            "n_train_global": self.n_train_global,
            "n_feat": self.n_feat,
            "n_class": self.n_class,
            "multilabel": self.multilabel,
            "source_edge_checksum": self.source_edge_checksum,
            "reorder": self.reorder,
            "layout_version": self.layout_version,
        }
        if trim_edges:
            manifest["trimmed_edges"] = True
        # the permutation arrays exist only on reordered layouts, so
        # they are saved conditionally — pre-reorder readers of the
        # fixed _ARRAYS list stay compatible either way
        extra = [k for k in self._REORDER_ARRAYS
                 if getattr(self, k) is not None]
        # arrays first, manifest last: exists() keys off the manifest, so
        # a reader polling a shared filesystem (multi-host prepare) never
        # observes a half-written artifact
        if mmap:
            adir = os.path.join(path, "arrays")
            os.makedirs(adir, exist_ok=True)
            for k in self._ARRAYS + extra:
                if trim_edges and k in ("edge_src", "edge_dst"):
                    arr = getattr(self, k)
                    for r in range(self.num_parts):
                        e_r = int(self.edge_count[r])
                        np.save(os.path.join(adir, f"{k}_r{r:03d}.npy"),
                                np.asarray(arr[r][:e_r]))
                    continue
                np.save(os.path.join(adir, f"{k}.npy"), getattr(self, k))
        else:
            np.savez_compressed(
                os.path.join(path, "arrays.npz"),
                **{k: getattr(self, k) for k in self._ARRAYS + extra},
            )
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)

    @staticmethod
    def load(path: str, parts=None) -> "ShardedGraph":
        """Load an artifact; `parts` (optional) is the global partition
        ids this process will own under the current elastic membership
        assignment — validated immediately (validate_assignment) so a
        redistributed relaunch pointed at a half-synced or mismatched
        artifact fails AT LOAD, not mid-epoch inside a collective."""
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        version = manifest.pop("format_version", 0)
        if version == ShardedGraph.MMAP_FORMAT_VERSION:
            trimmed = manifest.pop("trimmed_edges", False)
            adir = os.path.join(path, "arrays")
            arrays = {}
            for k in ShardedGraph._ARRAYS:
                if trimmed and k in ("edge_src", "edge_dst"):
                    arrays[k] = _RaggedEdges(adir, k,
                                             manifest["num_parts"])
                    continue
                arrays[k] = np.load(os.path.join(adir, f"{k}.npy"),
                                    mmap_mode="r")
            for k in ShardedGraph._REORDER_ARRAYS:
                p = os.path.join(adir, f"{k}.npy")
                if os.path.exists(p):
                    arrays[k] = np.load(p, mmap_mode="r")
            sg = ShardedGraph(**manifest, cache_dir=path, **arrays)
            if sg.reorder != "none":
                sg.validate_layout()
            if parts is not None:
                sg.validate_assignment(parts)
            return sg
        if version != ShardedGraph.FORMAT_VERSION:
            raise ValueError(
                f"partition artifact at {path} has format v{version}, "
                f"expected v{ShardedGraph.FORMAT_VERSION} (or mmap "
                f"v{ShardedGraph.MMAP_FORMAT_VERSION}); re-partition "
                f"(delete the directory or drop --skip-partition)"
            )
        arrays = np.load(os.path.join(path, "arrays.npz"))
        keys = ShardedGraph._ARRAYS + [k for k in
                                       ShardedGraph._REORDER_ARRAYS
                                       if k in arrays.files]
        sg = ShardedGraph(**manifest, cache_dir=path,
                          **{k: arrays[k] for k in keys})
        if sg.reorder != "none":
            sg.validate_layout()
        if parts is not None:
            sg.validate_assignment(parts)
        return sg

    def validate_assignment(self, parts) -> None:
        """Assignment-aware artifact check for elastic membership
        (resilience/elastic.py): `parts` must be distinct in-range
        partition ids, and for a trim_edges v3 artifact every per-rank
        edge file those partitions need must actually exist on THIS
        host — after redistribution a process opens ranks it never
        touched before, and a partially-synced shared filesystem must
        fail loudly here instead of as a FileNotFoundError three
        layers down. Records the set on ``local_parts``."""
        ids = sorted(int(p) for p in parts)
        if len(set(ids)) != len(ids):
            raise ValueError(
                f"assignment validation: duplicate partition ids in "
                f"{list(parts)}")
        if ids and (ids[0] < 0 or ids[-1] >= self.num_parts):
            raise ValueError(
                f"assignment validation: partition ids {ids} out of "
                f"range [0, {self.num_parts}) — membership assignment "
                f"and artifact disagree (stale ledger or wrong "
                f"--n-partitions?)")
        for key in ("edge_src", "edge_dst"):
            arr = getattr(self, key)
            if isinstance(arr, _RaggedEdges):
                missing = [
                    r for r in ids
                    if not os.path.exists(os.path.join(
                        arr._adir, f"{key}_r{r:03d}.npy"))]
                if missing:
                    raise ValueError(
                        f"assignment validation: trimmed artifact is "
                        f"missing {key} files for newly-assigned "
                        f"partitions {missing} (half-synced artifact "
                        f"directory?)")
        self.local_parts = tuple(ids)

    def validate_layout(self) -> None:
        """Loud host-side boundary-slot / permutation validation (the
        same contract as ops.bucket_spmm.validate_bucket_tables): every
        send-list entry must name a real inner node of its sender, and
        a reordered layout's permutation arrays must be present and
        mutually inverse per rank. Raises a named ValueError on the
        first violated invariant — a silent mismatch here becomes
        garbage halo rows (wrong features exchanged), not a crash."""
        P = self.num_parts
        for r in range(P):
            ic = int(self.inner_count[r])
            for d in range(P - 1):
                c = int(self.send_counts[r, d])
                if not c:
                    continue
                idx = np.asarray(self.send_idx[r, d, :c])
                if idx.min() < 0 or idx.max() >= ic:
                    raise ValueError(
                        f"boundary-slot validation: send_idx[r={r}, "
                        f"dist={d + 1}] references local id "
                        f"{int(idx.min())}..{int(idx.max())} outside "
                        f"[0, {ic}) — send lists and node layout "
                        f"disagree (stale or mismatched reorder "
                        f"permutation?)")
        has_perm = self.reorder_perm is not None
        if (self.reorder != "none") != has_perm or \
                has_perm == (self.reorder_inv is None):
            raise ValueError(
                f"boundary-slot validation: reorder tag "
                f"{self.reorder!r} but permutation arrays "
                f"{'present' if has_perm else 'absent'} — layout "
                f"metadata is inconsistent (rebuild the artifact)")
        if not has_perm:
            return
        perm = np.asarray(self.reorder_perm)
        inv = np.asarray(self.reorder_inv)
        want = (P, self.n_max)
        if perm.shape != want or inv.shape != want:
            raise ValueError(
                f"boundary-slot validation: reorder permutation shape "
                f"{perm.shape}/{inv.shape} != {want} — permutation/"
                f"table mismatch (artifact built for another layout?)")
        ar = np.arange(self.n_max)
        for r in range(P):
            ic = int(self.inner_count[r])
            p_r, i_r = perm[r, :ic], inv[r, :ic]
            if not (np.array_equal(np.sort(p_r), ar[:ic])
                    and np.array_equal(i_r[p_r], ar[:ic])):
                raise ValueError(
                    f"boundary-slot validation: reorder_perm/"
                    f"reorder_inv of rank {r} are not mutually inverse "
                    f"permutations of [0, {ic}) — permutation/table "
                    f"mismatch")
            if ic < self.n_max and not (perm[r, ic:] == -1).all():
                raise ValueError(
                    f"boundary-slot validation: reorder_perm rank {r} "
                    f"padding rows not -1 — permutation/table mismatch")

    @staticmethod
    def exists(path: str) -> bool:
        return os.path.exists(os.path.join(path, "manifest.json"))
