"""Graph partitioner.

Replaces the reference's dependency on METIS via a customized DGL fork
(reference helper/utils.py:132-144; the fork exists only to pass
`objtype` through to METIS, README.md:62). Supported surface is the same:

    method = 'metis' | 'random'     (reference helper/parser.py:39-42)
    obj    = 'vol' | 'cut'

'metis' here is a self-contained locality-aware partitioner, fully
vectorized so it scales to 100M+ edge graphs on host:

    1. BFS ordering of the whole graph (random restart per connected
       component) — nodes close in the graph are close in the order;
    2. contiguous balanced blocks of that order as the initial partition;
    3. parallel greedy refinement sweeps moving boundary nodes to the
       neighboring partition with the best objective gain, subject to a
       balance cap (a vectorized, conflict-tolerant variant of
       Fiduccia–Mattheyses, in the spirit of parallel refiners like Jet).

It is not METIS, but fills the same role; partition quality affects
communication volume, not correctness.

When the native C++ multilevel partitioner (pipegcn_tpu.native:
heavy-edge-matching coarsening + FM refinement, the same algorithm
family as METIS itself) is buildable, 'metis' dispatches to it — it
produces substantially better cuts than the flat Python refiner and is
faster. PIPEGCN_NATIVE=0 forces the pure-numpy path.

Objectives:
    'cut' — minimize the number of edges crossing partitions.
    'vol' — minimize total communication volume: the number of distinct
            (node, foreign-partition) pairs, i.e. how many halo rows get
            exchanged per layer. This is the objective that matters for
            PipeGCN-style training.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from ..graph.csr import Graph


def partition_graph(
    g: Graph,
    n_parts: int,
    method: str = "metis",
    obj: str = "vol",
    seed: int = 0,
    refine_iters: int = 10,
    imbalance: float = 1.05,
    symmetric: bool = False,
) -> np.ndarray:
    """Assign each node to one of `n_parts` partitions.

    Returns an int32 array [num_nodes] of partition ids. Every partition is
    guaranteed non-empty (each device must own at least one node).

    `symmetric=True` asserts g's edge list is already mirrored (e.g.
    the papers100M finalized-edge cache): the adjacency is then built
    WITHOUT the doubling mirror — at billion-edge scale the difference
    is ~50 GB of transient.
    """
    if n_parts <= 0:
        raise ValueError(f"n_parts must be positive, got {n_parts}")
    if method not in ("metis", "random"):
        raise ValueError(f"unknown partition method: {method}")
    if obj not in ("vol", "cut"):
        raise ValueError(f"unknown partition objective: {obj}")
    if n_parts > g.num_nodes:
        raise ValueError(
            f"n_parts={n_parts} exceeds num_nodes={g.num_nodes}"
        )
    if n_parts == 1:
        return np.zeros(g.num_nodes, dtype=np.int32)

    rng = np.random.default_rng(seed)
    if method == "random":
        # Balanced random assignment (reference part_method='random').
        parts = np.repeat(
            np.arange(n_parts, dtype=np.int32), -(-g.num_nodes // n_parts)
        )[: g.num_nodes]
        rng.shuffle(parts)
        return parts

    from .. import native

    if symmetric or g.num_edges > _CHUNKED_ADJ_EDGES:
        # RAM-bounded path: counting-sort CSR build (no scipy COO,
        # whose doubled u/v int64 buffers alone cost ~100 GB at
        # papers100M scale). Duplicate/bidirectional edges stay as
        # parallel unit-weight entries — mutual pairs effectively weigh
        # 2 vs a one-way edge's 1 (an approximation vs _sym_adj's
        # dedup-to-1; exact when the input is uniformly mirrored, as
        # symmetric=True asserts)
        indptr, indices = _csr_adjacency_chunked(g, symmetric=symmetric)
        adj = None
    else:
        adj = _sym_adj(g)
        indptr = adj.indptr.astype(np.int64)
        indices = adj.indices.astype(np.int32)
    if native.available():
        return native.native_partition(
            indptr, indices, n_parts, obj=obj, seed=seed,
            imbalance=imbalance, refine_iters=refine_iters,
        )
    if adj is None:  # numpy fallback needs the scipy structure
        adj = sp.csr_matrix(
            (np.ones(indices.shape[0], np.int8), indices, indptr),
            shape=(g.num_nodes, g.num_nodes))

    order = _bfs_order(adj, rng)
    # contiguous balanced blocks of the BFS order
    parts = np.empty(g.num_nodes, dtype=np.int32)
    parts[order] = (
        np.arange(g.num_nodes, dtype=np.int64) * n_parts // g.num_nodes
    ).astype(np.int32)
    parts = _refine(adj, parts, n_parts, obj, refine_iters, imbalance, rng)
    return parts


# default locality-cluster granularity; artifact cache keys derive
# from it via cluster_suffix so every consumer shares ONE definition
# of "which layout is this". 1024 beat the earlier 4096 default on
# the chip (1.5182 vs 1.5935 s/epoch, results/tpu_bench.md): same
# 80% dense coverage from 2.4x fewer, denser tiles.
DEFAULT_CLUSTER_SIZE = 1024


def cluster_suffix(target_size: int) -> str:
    """Artifact-name fragment identifying the cluster layout. Always
    encodes the size: identity must be self-describing, not relative
    to DEFAULT_CLUSTER_SIZE — a default-relative '' suffix silently
    re-mapped cached artifacts when the default moved 4096 -> 1024."""
    return f"s{target_size}"


def locality_clusters(
    g: Graph,
    target_size: int = DEFAULT_CLUSTER_SIZE,
    seed: int = 0,
) -> np.ndarray:
    """Cluster labels for locality-aware LOCAL renumbering.

    Orders of magnitude finer than the device partitioning: ~target_size
    nodes per cluster. ShardedGraph.build sorts each partition's inner
    nodes by these labels, so nodes of one community get contiguous
    local ids and the shard's adjacency concentrates into dense tiles —
    the structure ops/block_spmm.py's MXU path needs. (The reference
    inherits whatever order DGL's METIS emits; here locality is an
    explicit, separately-controlled step.)

    Uses the same partitioner machinery with k = ceil(n / target_size);
    returns zeros (single cluster, no-op ordering) for graphs at or
    below target_size.
    """
    k = max(1, -(-g.num_nodes // target_size))
    from .. import native

    if not native.available():
        # the pure-numpy refiner materializes dense [N, k] gain tables;
        # cap k so that stays ~256 MB instead of OOMing on large graphs
        # (coarser clusters = coarser locality, still valid ordering)
        k = min(k, max(1, (64 << 20) // max(g.num_nodes, 1)))
    if k == 1:
        return np.zeros(g.num_nodes, dtype=np.int32)
    # higher imbalance tolerance than device partitioning: clusters only
    # steer ordering, so balance is irrelevant — cut quality is all that
    # matters
    return partition_graph(g, k, method="metis", obj="cut", seed=seed,
                           refine_iters=6, imbalance=1.3)


# above this many edges the scipy COO symmetrize is replaced by the
# chunked counting-sort CSR build (RAM: ~3x edge bytes vs ~30x)
_CHUNKED_ADJ_EDGES = 50_000_000


# locality reorder modes for ShardedGraph local renumbering. "auto" is
# resolved by measurement (ops/tuner.choose_reorder), never stored: an
# artifact's layout tag is always one of these concrete modes.
REORDER_MODES = ("none", "degree", "bfs", "degree-bfs")


def reorder_suffix(mode: str) -> str:
    """Artifact-name fragment identifying the reorder layout. 'none'
    maps to '' so pre-reorder artifact names stay valid cache keys."""
    if mode not in REORDER_MODES:
        raise ValueError(f"unknown reorder mode: {mode!r} "
                         f"(expected one of {REORDER_MODES})")
    return "" if mode == "none" else f"-r{mode}"


def reorder_key(g: Graph, mode: str, seed: int = 0):
    """Per-node int64 sort key realizing the locality reordering.

    ShardedGraph.build inserts this key into its local-id lexsort below
    the (partition, train-segment) keys, so within each partition's
    train and non-train segments inner nodes are renumbered:

      'degree'     — degree-bucket-major (power-of-two in-degree
                     buckets, hubs first), global-id-minor;
      'bfs'        — BFS-locality order (graph neighbors get nearby
                     local ids, so neighbor-gather index streams of the
                     SpMM kernels collapse into contiguous runs);
      'degree-bfs' — degree-bucket-major, BFS-locality-minor: bucket
                     structure aligned with ops/bucket_spmm's ladder
                     AND run-friendly gather streams inside each bucket.

    Returns None for 'none' (layout unchanged). The key is a pure
    ordering choice — ShardedGraph permutes features/labels/masks/CSR/
    send-lists coherently, so training semantics are untouched.
    """
    if mode in (None, "none"):
        return None
    if mode not in REORDER_MODES:
        raise ValueError(f"unknown reorder mode: {mode!r} "
                         f"(expected one of {REORDER_MODES})")
    n = g.num_nodes
    minor = np.arange(n, dtype=np.int64)
    if mode in ("bfs", "degree-bfs"):
        rng = np.random.default_rng(seed)
        if g.num_edges > _CHUNKED_ADJ_EDGES:
            indptr, indices = _csr_adjacency_chunked(g)
            adj = sp.csr_matrix(
                (np.ones(indices.shape[0], np.int8), indices, indptr),
                shape=(n, n))
        else:
            adj = _sym_adj(g)
        order = _bfs_order(adj, rng)
        minor = np.empty(n, dtype=np.int64)
        minor[order] = np.arange(n, dtype=np.int64)
    if mode == "bfs":
        return minor
    # hubs first: the highest-degree rows are gathered most often, so
    # packing them into the lowest local ids concentrates the hot
    # working set into one compact, streamable id range
    deg = g.in_degrees().astype(np.int64)
    bucket = np.floor(np.log2(np.maximum(deg, 1))).astype(np.int64)
    return (int(bucket.max()) - bucket) * n + minor


def _csr_adjacency_chunked(g: Graph, symmetric: bool = False,
                           chunk: int = 32_000_000):
    """Self-loop-free CSR adjacency (indptr int64, indices int32) built
    by a two-pass chunked counting sort — peak transient is O(chunk),
    plus the output arrays themselves. With symmetric=False each edge
    is filled in both directions (no dedup: a bidirectional input pair
    contributes weight 2 per direction, uniformly — equivalent for the
    partition objectives); with symmetric=True the input is trusted to
    be mirrored already and filled as-is. Sources may be memmaps."""
    n = g.num_nodes
    counts = np.zeros(n, np.int64)
    E = g.src.shape[0]
    for i in range(0, E, chunk):
        s = np.asarray(g.src[i:i + chunk])
        d = np.asarray(g.dst[i:i + chunk])
        m = s != d
        s, d = s[m], d[m]
        counts += np.bincount(s, minlength=n)
        if not symmetric:
            counts += np.bincount(d, minlength=n)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    del counts
    indices = np.empty(indptr[-1], np.int32)
    cursor = indptr[:-1].copy()

    def fill(s, d):
        if s.shape[0] == 0:
            return
        order = np.argsort(s, kind="stable")
        ss = s[order]
        dd = d[order]
        starts = np.concatenate(
            [[0], np.flatnonzero(np.diff(ss)) + 1])
        lens = np.diff(np.concatenate([starts, [ss.shape[0]]]))
        within = np.arange(ss.shape[0], dtype=np.int64) \
            - np.repeat(starts, lens)
        indices[cursor[ss] + within] = dd
        cursor[ss[starts]] += lens

    for i in range(0, E, chunk):
        s = np.asarray(g.src[i:i + chunk]).astype(np.int64, copy=False)
        d = np.asarray(g.dst[i:i + chunk]).astype(np.int64, copy=False)
        m = s != d
        s, d = s[m], d[m]
        fill(s, d)
        if not symmetric:
            fill(d, s)
    return indptr, indices


def _sym_adj(g: Graph) -> sp.csr_matrix:
    """Symmetric 0/1 adjacency without self loops."""
    non_loop = g.src != g.dst
    u = np.concatenate([g.src[non_loop], g.dst[non_loop]])
    v = np.concatenate([g.dst[non_loop], g.src[non_loop]])
    n = g.num_nodes
    a = sp.csr_matrix(
        (np.ones(u.shape[0], dtype=np.int32), (u, v)), shape=(n, n)
    )
    a.data[:] = 1  # collapse duplicate edges
    return a


def _bfs_order(adj: sp.csr_matrix, rng) -> np.ndarray:
    """Vectorized BFS ordering covering all components (restart at a random
    unvisited node per component)."""
    n = adj.shape[0]
    visited = np.zeros(n, dtype=bool)
    order = np.empty(n, dtype=np.int64)
    pos = 0
    # restart cursor over a fixed random permutation: amortized O(N) over
    # all components instead of an O(N) scan per component
    restart_perm = rng.permutation(n)
    cursor = 0
    while pos < n:
        while cursor < n and visited[restart_perm[cursor]]:
            cursor += 1
        start = int(restart_perm[cursor])
        frontier = np.array([start])
        visited[start] = True
        order[pos] = start
        pos += 1
        while frontier.size:
            # union of neighbors of the frontier, via one sparse matvec
            ind = np.unique(adj[frontier].indices)
            ind = ind[~visited[ind]]
            if ind.size == 0:
                break
            visited[ind] = True
            order[pos: pos + ind.size] = ind
            pos += ind.size
            frontier = ind
    return order


def _refine(
    adj: sp.csr_matrix,
    parts: np.ndarray,
    n_parts: int,
    obj: str,
    iters: int,
    imbalance: float,
    rng,
) -> np.ndarray:
    """Parallel greedy refinement. Each sweep computes, for every node, its
    neighbor count per partition (one sparse-dense matmul), derives move
    gains for the requested objective, and applies the highest-gain moves
    subject to the per-partition balance cap."""
    n = adj.shape[0]
    parts = parts.astype(np.int32).copy()
    cap = int(imbalance * (-(-n // n_parts)))
    arange = np.arange(n)

    for _ in range(iters):
        onehot = sp.csr_matrix(
            (np.ones(n, dtype=np.float32), (arange, parts)),
            shape=(n, n_parts),
        )
        counts = np.asarray((adj @ onehot).todense())  # [N, P]
        own = counts[arange, parts]
        if obj == "cut":
            gains = counts - own[:, None]
        else:  # vol: also count the halo pairs this node creates/removes
            gains = (
                counts
                - own[:, None]
                + (counts > 0).astype(np.float32)
                - (own > 0).astype(np.float32)[:, None]
            )
        gains[arange, parts] = -np.inf
        target = np.argmax(gains, axis=1).astype(np.int32)
        gain = gains[arange, target]
        movers = np.nonzero(gain > 0)[0]
        if movers.size == 0:
            break

        # enforce balance: admit the best movers into each target part up
        # to its remaining room, and never drain a part empty
        sizes = np.bincount(parts, minlength=n_parts)
        room = np.maximum(cap - sizes, 0)
        # sort movers by (target, -gain); rank within target group
        key = np.lexsort((-gain[movers], target[movers]))
        movers = movers[key]
        tgt = target[movers]
        grp_start = np.searchsorted(tgt, np.arange(n_parts))
        rank = arange[: movers.size] - grp_start[tgt]
        admitted = movers[rank < room[tgt]]
        if admitted.size == 0:
            break
        parts[admitted] = target[admitted]
        _fill_empty_parts(parts, n_parts)
    _fill_empty_parts(parts, n_parts)
    return parts


def _fill_empty_parts(parts: np.ndarray, n_parts: int) -> None:
    """Ensure every partition owns at least one node (each device must hold
    a shard); steal single nodes from the currently largest partition."""
    sizes = np.bincount(parts, minlength=n_parts)
    for p in np.nonzero(sizes == 0)[0]:
        donor = int(np.argmax(sizes))
        parts[np.nonzero(parts == donor)[0][0]] = p
        sizes[donor] -= 1
        sizes[p] += 1


def edge_cut(g: Graph, parts: np.ndarray) -> int:
    """Number of non-self-loop directed edges crossing partitions."""
    non_loop = g.src != g.dst
    return int((parts[g.src[non_loop]] != parts[g.dst[non_loop]]).sum())


def comm_volume(g: Graph, parts: np.ndarray) -> int:
    """Total halo pairs: distinct (node, foreign partition consuming it)."""
    non_loop = g.src != g.dst
    src, dst = g.src[non_loop], g.dst[non_loop]
    cross = parts[src] != parts[dst]
    pairs = np.unique(
        np.stack([src[cross], parts[dst[cross]].astype(np.int64)], 1), axis=0
    )
    return int(pairs.shape[0])
