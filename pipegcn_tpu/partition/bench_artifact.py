"""Canonical bench partition-artifact recipe, shared by bench.py and
the window-queue probe scripts.

partitions/ is not git-tracked, so artifacts vanish between rounds;
every consumer goes through :func:`ensure` (or :func:`build_artifact`
for non-canonical datasets) instead of failing — or re-implementing
the recipe: the dataset string, the ``c2`` generator revision, the
cluster suffix and the reorder suffix are artifact *identity* and must
live in exactly one place.

No reference counterpart: the reference caches DGL partition JSONs on
disk keyed by graph_name (helper/utils.py:137); this is the analogous
cache plus self-describing naming for the synthetic bench graphs.
"""

from __future__ import annotations

import os
import re
import shutil
import time

GEN_REV = "2"  # synthetic-graph generator revision (deduped pairs)

# regex over the self-describing artifact basename:
#   bench-{reddit|small}-{n_parts}-c{rev}-s{cluster_size}[-r{reorder}]
# (no -r suffix == reorder "none": pre-reorder names stay valid keys)
_NAME_RE = re.compile(
    r"bench-(reddit|small)-(\d+)-c(\d+)-s(\d+)"
    r"(?:-r(degree-bfs|degree|bfs))?")


def artifact_path(n_parts: int, cluster_size: int, small: bool = False,
                  root: str = "partitions",
                  reorder: str = "none") -> str:
    from .partitioner import cluster_suffix, reorder_suffix

    name = f"bench-small-{n_parts}" if small else f"bench-reddit-{n_parts}"
    return os.path.join(root, f"{name}-c{GEN_REV}-"
                              f"{cluster_suffix(cluster_size)}"
                              f"{reorder_suffix(reorder)}")


def parse_artifact_name(path: str):
    """(small, n_parts, cluster_size, reorder) from a bench artifact
    path, or None when the basename is not a bench artifact (exact
    match only — substring guards once confused s1024 with s10240)."""
    m = _NAME_RE.fullmatch(os.path.basename(path))
    if not m or m.group(3) != GEN_REV:
        return None
    return (m.group(1) == "small", int(m.group(2)), int(m.group(4)),
            m.group(5) or "none")


def _publish(sg, path: str, log) -> None:
    """Atomically move a built ShardedGraph save into ``path``.

    Race-tolerant: builds land in a per-pid temp sibling; whoever
    renames first wins, losers discard their copy. A stale
    manifest-less dir at ``path`` (a save killed mid-write before this
    scheme existed) is renamed aside into a per-pid trash sibling and
    deleted THERE — readers never observe a half-deleted dir at
    ``path``, and the validity check happens immediately before the
    single atomic rename, so the window in which a concurrent winner's
    fresh artifact could be displaced is one rename wide (not the
    length of an rmtree). Even then both builds are deterministic
    copies of the same artifact, so the re-publish is identical.
    """
    from . import ShardedGraph

    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    sg.save(tmp)
    try:
        os.rename(tmp, path)
        return
    except OSError:
        # load-bearing but locally handled (storage-fault audit): the
        # fall-through below re-checks, displaces, retries, and RAISES
        # RuntimeError when nothing publishable lands — publish failure
        # is never silent
        pass
    # re-check RIGHT before displacing anything: a concurrent winner
    # may have renamed a valid artifact into place since our failed
    # rename above
    if not ShardedGraph.exists(path) and os.path.isdir(path):
        log(f"# replacing stale non-artifact dir at {path}")
        trash = f"{path}.trash-{os.getpid()}"
        try:
            os.rename(path, trash)  # aside, never rmtree in place
        except OSError:
            pass  # a concurrent builder displaced it first
        else:
            shutil.rmtree(trash, ignore_errors=True)
        try:
            os.rename(tmp, path)
            return
        except OSError:
            pass  # concurrent builder racing on the same stale dir
    if ShardedGraph.exists(path):  # a concurrent builder won
        shutil.rmtree(tmp, ignore_errors=True)
        return
    raise RuntimeError(f"could not publish artifact into {path} "
                       f"(build left at {tmp})")


def build_artifact(dataset: str, n_parts: int, cluster_size: int,
                   path: str, log=print, reorder: str = "none"):
    """Build + publish the partition artifact for ``dataset`` at
    ``path``; returns the in-memory ShardedGraph (cache_dir set). Pure
    host numpy — no jax import, safe from a chip-backend process."""
    from . import ShardedGraph
    from ..graph import load_data
    from .partitioner import locality_clusters, partition_graph

    t0 = time.perf_counter()
    g = load_data(dataset)
    log(f"# loaded {dataset} ({time.perf_counter()-t0:.1f}s)")
    parts = partition_graph(g, n_parts, method="metis", obj="vol", seed=0)
    cluster = locality_clusters(g, target_size=cluster_size, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=n_parts, cluster=cluster,
                            reorder=reorder)
    _publish(sg, path, log)
    log(f"# built {path} ({time.perf_counter()-t0:.1f}s)")
    sg.cache_dir = path  # derived kernel tables cache with the artifact
    return sg


def ensure(path: str, log=print):
    """Load the bench artifact at ``path``, building it first if
    missing; returns the :class:`ShardedGraph`."""
    from . import ShardedGraph

    if ShardedGraph.exists(path):
        return ShardedGraph.load(path)
    parsed = parse_artifact_name(path)
    if parsed is None:
        raise FileNotFoundError(
            f"{path}: artifact missing and not a canonical bench name "
            f"(expected bench-{{reddit|small}}-N-c{GEN_REV}-sC"
            f"[-rREORDER])")
    small, n_parts, cluster_size, reorder = parsed
    dataset = "synthetic:10000:20:64:16" if small else "synthetic-reddit"
    return build_artifact(dataset, n_parts, cluster_size, path, log=log,
                          reorder=reorder)


def resolve_reorder(n_parts: int, cluster_size: int, small: bool,
                    root: str, reorder: str, log=print) -> str:
    """Resolve ``--reorder auto`` to a concrete artifact layout.

    Preference order: (1) any already-built bench artifact for this
    shape (cheapest — reuse what exists, reordered variants first);
    (2) otherwise a MEASURED decision: build the dataset graph once,
    time a degree-distribution-preserving sampled slice under the
    'none' and 'degree-bfs' layouts (ops.tuner.choose_reorder) and
    take the winner. Concrete modes pass through unchanged, so
    callers can always treat the return value as artifact identity.
    """
    if reorder != "auto":
        return reorder
    from . import ShardedGraph

    candidates = ["degree-bfs", "degree", "bfs", "none"]
    for mode in candidates:
        p = artifact_path(n_parts, cluster_size, small, root, mode)
        if ShardedGraph.exists(p):
            log(f"# --reorder auto: reusing existing artifact {p}")
            return mode
    from ..graph import load_data
    from ..ops.tuner import choose_reorder

    dataset = "synthetic:10000:20:64:16" if small else "synthetic-reddit"
    g = load_data(dataset)
    mode, timings = choose_reorder(g, log=log)
    log(f"# --reorder auto -> {mode} (measured {timings})")
    return mode
