from .checkpoint import (
    CheckpointCorrupt,
    checkpoint_exists,
    load_checkpoint,
    load_pytree,
    peek_epoch,
    save_checkpoint,
    save_pytree,
)
from .timer import CommTimer

__all__ = [
    "save_pytree",
    "load_pytree",
    "save_checkpoint",
    "load_checkpoint",
    "checkpoint_exists",
    "peek_epoch",
    "CheckpointCorrupt",
    "CommTimer",
]
