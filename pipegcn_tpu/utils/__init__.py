from .checkpoint import save_pytree, load_pytree, save_checkpoint, load_checkpoint

__all__ = ["save_pytree", "load_pytree", "save_checkpoint", "load_checkpoint"]
