from .checkpoint import save_pytree, load_pytree, save_checkpoint, load_checkpoint
from .timer import CommTimer

__all__ = [
    "save_pytree",
    "load_pytree",
    "save_checkpoint",
    "load_checkpoint",
    "CommTimer",
]
