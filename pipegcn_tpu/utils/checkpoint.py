"""Checkpointing: pytrees <-> npz files, hardened for production runs.

The reference only saves the best model's state_dict at the end of
training (train.py:397) — and into a directory it never creates (latent
crash, SURVEY.md §2a). Here: directories are created, and full training
state (params + optimizer moments + norm state + pipelined comm buffers +
epoch) can be checkpointed and resumed, which the reference cannot do.

Format: one .npz per pytree, leaves keyed by their tree path; loading
restores into the structure of a caller-provided template pytree (shapes
and paths must match).

Hardening (docs/RESILIENCE.md):

  - every stored array carries a CRC32 digest (over dtype+shape+bytes)
    in a ``__digests__`` manifest inside the npz; loads verify what
    they read, so silent bit-rot on a shared filesystem surfaces as
    :class:`CheckpointCorrupt` instead of NaNs three epochs later
  - a checkpoint directory holds keep-last-N *generations*
    (``state-<epoch08d>.npz``) plus a ``latest`` pointer file;
    :func:`load_checkpoint` falls back to the previous good generation
    when the newest fails verification
  - truncated / torn / scribbled archives (zipfile.BadZipFile, EOF,
    zlib errors) raise :class:`CheckpointCorrupt` rather than escaping
    raw, so the rotation fallback — and callers like ``peek_epoch`` —
    can handle them
  - the legacy single-file ``state.npz`` layout still loads (as the
    oldest-priority candidate), so pre-rotation checkpoints resume
"""

from __future__ import annotations

import json
import os
import re
import shutil
import warnings
import zipfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file failed to open, read, or verify."""


def _io():
    # lazy: resilience/__init__ -> elastic -> this module would cycle
    # on a top-level import of the storage shim
    from ..resilience.storage import FAULTY_IO
    return FAULTY_IO


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


_BF16 = np.dtype(jax.numpy.bfloat16.dtype)
# np.savez round-trips ml_dtypes.bfloat16 as raw void ('|V2'); store such
# leaves as a uint16 view under a tagged key instead
_BF16_TAG = "__bf16__/"
# JSON manifest {stored key: crc32} written alongside the arrays
_DIGEST_KEY = "__digests__"

# read-side failure modes of a truncated/scribbled npz: the zip central
# directory (BadZipFile), a short member (EOFError/OSError), or the
# member's deflate stream (zlib.error)
_READ_ERRORS = (zipfile.BadZipFile, EOFError, OSError, zlib.error)


def _crc(arr: np.ndarray) -> int:
    """CRC32 over dtype + shape + raw bytes: a reinterpreted view or a
    resized array must not collide with the original."""
    arr = np.ascontiguousarray(arr)
    h = zlib.crc32(f"{arr.dtype.str}|{arr.shape}|".encode())
    return zlib.crc32(arr.tobytes(), h) & 0xFFFFFFFF


def save_pytree(path: str, tree: Any, extra: dict = None) -> None:
    """`extra` adds raw scalar/array entries (e.g. the checkpoint
    epoch) to the npz; load_pytree ignores them (it reads only the
    template's paths)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = {k: np.asarray(v) for k, v in (extra or {}).items()}
    for p, v in leaves:
        arr = np.asarray(v)
        key = _path_str(p)
        if arr.dtype == _BF16:
            arrays[_BF16_TAG + key] = arr.view(np.uint16)
        else:
            arrays[key] = arr
    arrays[_DIGEST_KEY] = np.asarray(
        json.dumps({k: _crc(v) for k, v in arrays.items()}))
    # temp + atomic rename: an interrupted save (disk full, SIGTERM,
    # crash-handler save racing a second failure) must never destroy
    # the previous good checkpoint at `path`. The pid in the temp name
    # keeps multi-host SPMD processes — which all save the same state
    # to the same shared-filesystem path — from renaming each other's
    # half-written temp away (observed as FileNotFoundError on rank 1).
    # (np.savez appends ".npz" unless the name already ends with it)
    io = _io()
    io.gate(path, "open")
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    try:
        np.savez_compressed(tmp, **arrays)
        io.gate(path, "write")
        io.maybe_tear(tmp)
        io.gate(path, "rename")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def load_pytree(path: str, template: Any, *, with_extras: bool = False,
                verify: bool = True):
    """Load arrays saved by save_pytree into template's structure.

    With with_extras=True returns (tree, extras) where extras holds the
    non-leaf keys (the `extra=` dict passed to save_pytree), so callers
    needing both never reopen the archive.

    verify=True (default) checks each array it reads against the
    ``__digests__`` manifest when one is present (files written before
    the manifest existed load unverified). Open/read failures and
    digest mismatches raise :class:`CheckpointCorrupt`; a missing leaf
    or shape mismatch still raises KeyError/ValueError — those are
    template/config errors, not file corruption."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    leaf_keys = set()
    extras = {}
    try:
        data = np.load(path)
    except _READ_ERRORS as exc:
        raise CheckpointCorrupt(
            f"cannot open checkpoint {path}: {exc!r}") from exc
    try:
        digests = None
        if verify and _DIGEST_KEY in data.files:
            try:
                digests = json.loads(str(data[_DIGEST_KEY][()]))
            except (*_READ_ERRORS, ValueError) as exc:
                raise CheckpointCorrupt(
                    f"unreadable digest manifest in {path}: {exc!r}"
                ) from exc

        def read(key: str) -> np.ndarray:
            try:
                arr = data[key]
            except _READ_ERRORS as exc:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: member {key!r} unreadable "
                    f"({exc!r})") from exc
            if digests is not None and key in digests \
                    and _crc(arr) != digests[key]:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: digest mismatch for {key!r}")
            return arr

        for p, tmpl in paths:
            key = _path_str(p)
            if _BF16_TAG + key in data:
                arr = read(_BF16_TAG + key).view(_BF16)
                leaf_keys.add(_BF16_TAG + key)
            elif key in data:
                arr = read(key)
                leaf_keys.add(key)
            else:
                raise KeyError(f"checkpoint {path} missing leaf {key}")
            if tuple(arr.shape) != tuple(np.shape(tmpl)):
                raise ValueError(
                    f"checkpoint leaf {key}: shape {arr.shape} != template "
                    f"{np.shape(tmpl)}"
                )
            tdt = np.asarray(tmpl).dtype
            if arr.dtype != tdt:
                # e.g. resuming an f32-run checkpoint under --dtype
                # bfloat16: convert to the template's dtype so the restored
                # state matches the step's compiled avals
                arr = arr.astype(tdt)
            leaves.append(arr)
        if with_extras:
            for key in data.files:
                if key not in leaf_keys and key != _DIGEST_KEY:
                    extras[key] = read(key)
    finally:
        data.close()
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    return (tree, extras) if with_extras else tree


# ---------------- generations + latest pointer -------------------------

_GEN_RE = re.compile(r"^state-(\d{8})\.npz$")
_LATEST = "latest"


def _gen_name(epoch: int) -> str:
    return f"state-{epoch:08d}.npz"


def _generations(directory: str) -> List[Tuple[int, str]]:
    """[(epoch, path)] of on-disk generations, newest first; the legacy
    single-file ``state.npz`` (if any) rides last with epoch -1 so it
    is always the final fallback."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for n in names:
        m = _GEN_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, n)))
    out.sort(reverse=True)
    legacy = os.path.join(directory, "state.npz")
    if os.path.exists(legacy):
        out.append((-1, legacy))
    return out


def latest_checkpoint_path(directory: str) -> Optional[str]:
    """Path the ``latest`` pointer names — or the newest generation by
    filename when the pointer is missing/stale. None when the directory
    holds no checkpoint at all."""
    lp = os.path.join(directory, _LATEST)
    try:
        with open(lp) as f:
            name = os.path.basename(f.read().strip())
        p = os.path.join(directory, name)
        if name and os.path.exists(p):
            return p
    except OSError:
        pass
    gens = _generations(directory)
    return gens[0][1] if gens else None


def _candidates(directory: str) -> List[str]:
    """Load order: the latest pointer's target, then remaining
    generations newest-first, then the legacy state.npz."""
    first = latest_checkpoint_path(directory)
    out = [first] if first else []
    for _, p in _generations(directory):
        if p not in out:
            out.append(p)
    return out


def _estimate_nbytes(state: Any) -> int:
    """Upper bound on the serialized generation size: raw leaf bytes
    (savez_compressed only shrinks) + a fixed zip/manifest allowance."""
    total = 65536
    for leaf in jax.tree_util.tree_leaves(state):
        try:
            total += int(np.asarray(leaf).nbytes)
        except (TypeError, ValueError):
            pass
    return total


def disk_preflight(directory: str, state: Any,
                   margin_bytes: int = 64 << 20) -> bool:
    """True when `directory`'s filesystem has headroom for another
    generation of `state` (estimate + margin). False — space is tight —
    means the caller should still ATTEMPT the save (the estimate is an
    upper bound and the write is temp+rename-safe) but must not delete
    older generations to make room: never trade the only loadable
    generation for a write that may fail. Probe errors count as
    headroom — a broken statvfs must not fail an otherwise-healthy
    save path."""
    try:
        free = shutil.disk_usage(directory).free
    except OSError:
        return True
    return free >= _estimate_nbytes(state) + margin_bytes


def save_checkpoint(directory: str, state: Dict[str, Any], epoch: int,
                    keep: int = 3,
                    extra: Optional[Dict[str, Any]] = None) -> str:
    """Save full training state for resume; returns the generation path.

    The epoch rides INSIDE the npz (one atomic os.replace), so a crash
    between writes can never pair a new state with an old epoch number
    — which would double-step the optimizer on resume. Each save writes
    a new ``state-<epoch>.npz`` generation, repoints ``latest``
    atomically, and prunes generations beyond the newest `keep`
    (keep <= 0 keeps everything; the legacy state.npz is never
    pruned — it may be the only pre-rotation fallback). When the disk
    preflight says space is tight the save is still attempted but the
    prune is skipped for this save.

    `extra` rides alongside ``__epoch__`` inside the same npz (same
    atomicity guarantee) — the streaming path stamps its journal
    watermark (``__stream_seq__``, ``__topo_generation__``) here so a
    state can never be paired with the wrong topology position."""
    os.makedirs(directory, exist_ok=True)
    _sweep_stale_tmps(directory)
    headroom = disk_preflight(directory, state)
    if not headroom:
        warnings.warn(
            f"checkpoint disk preflight: {directory} is low on space "
            f"for another ~{_estimate_nbytes(state) >> 20} MiB "
            f"generation; attempting the save anyway but KEEPING all "
            f"older generations (rotation-deletion skipped)")
    path = os.path.join(directory, _gen_name(epoch))
    extras = {"__epoch__": np.asarray(epoch, np.int64)}
    if extra:
        for k, v in extra.items():
            extras[k] = np.asarray(v)
    save_pytree(path, state, extra=extras)
    io = _io()
    lp = os.path.join(directory, _LATEST)
    io.gate(lp, "open")
    tmp = f"{lp}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            io.gate(lp, "write")
            f.write(os.path.basename(path) + "\n")
        io.maybe_tear(tmp)
        io.gate(lp, "rename")
        os.replace(tmp, lp)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    if keep and keep > 0 and headroom:
        gens = [g for g in _generations(directory) if g[0] >= 0]
        for _, p in gens[keep:]:
            if os.path.abspath(p) == os.path.abspath(path):
                continue
            try:
                os.remove(p)
            except OSError:
                pass  # a still-open or vanished old generation is
                # not worth failing a successful save over
    return path


def _sweep_stale_tmps(directory: str, min_age_s: float = 3600.0) -> None:
    """Remove orphaned pid-named temps (*.tmp.npz, latest.*.tmp) left
    by a hard kill mid-save. Age-gated so a live peer process's
    in-flight temp (the multi-host concurrent-save case the pid naming
    exists for) is never touched."""
    import glob
    import time

    now = time.time()
    for pat in ("*.tmp.npz", f"{_LATEST}.*.tmp"):
        for tmp in glob.glob(os.path.join(directory, pat)):
            try:
                if now - os.path.getmtime(tmp) > min_age_s:
                    os.remove(tmp)
            except OSError:
                pass


def _legacy_epoch(directory: str) -> int:
    """Epoch of a pre-__epoch__ checkpoint layout (epoch.txt alongside
    state.npz). Raises CheckpointCorrupt if unreadable — a silent
    default would let callers resume from the wrong epoch."""
    try:
        with open(os.path.join(directory, "epoch.txt")) as f:
            return int(f.read().strip())
    except (OSError, ValueError) as exc:
        raise CheckpointCorrupt(
            f"legacy checkpoint in {directory} has no readable "
            f"epoch.txt ({exc!r})") from exc


def _epoch_of(path: str, directory: str) -> int:
    """Epoch recorded inside generation `path` (reads only the scalar;
    npz members load lazily)."""
    try:
        with np.load(path) as data:
            if "__epoch__" in data.files:
                return int(data["__epoch__"])
    except _READ_ERRORS as exc:
        raise CheckpointCorrupt(
            f"cannot read epoch from {path}: {exc!r}") from exc
    return _legacy_epoch(directory)


def load_checkpoint(directory: str, template: Dict[str, Any],
                    with_extras: bool = False):
    """Returns (state, next_epoch) restored from save_checkpoint —
    or (state, next_epoch, extras) when `with_extras` is True (the
    extras dict carries whatever rode along via ``save_checkpoint``'s
    `extra=`, e.g. the streaming watermark).

    Tries the ``latest`` generation first and falls back through older
    generations (warning on each corrupt one) — a torn or bit-rotted
    newest file costs the epochs since the previous save, not the run.
    Raises :class:`CheckpointCorrupt` when every candidate fails, and
    FileNotFoundError when there is no checkpoint at all."""
    cands = _candidates(directory)
    if not cands:
        raise FileNotFoundError(f"no checkpoint in {directory}")
    last_exc: Optional[CheckpointCorrupt] = None
    for path in cands:
        try:
            state, extras = load_pytree(path, template, with_extras=True)
            epoch = (int(extras["__epoch__"]) if "__epoch__" in extras
                     else _legacy_epoch(directory))
            if last_exc is not None:
                warnings.warn(
                    f"restored previous good checkpoint generation "
                    f"{os.path.basename(path)} (epoch {epoch})")
            if with_extras:
                return state, epoch, extras
            return state, epoch
        except CheckpointCorrupt as exc:
            last_exc = exc
            warnings.warn(
                f"checkpoint generation {os.path.basename(path)} failed "
                f"verification ({exc}); falling back")
    raise CheckpointCorrupt(
        f"every checkpoint generation in {directory} failed "
        f"verification; last error: {last_exc}")


def peek_watermark(directory: str) -> Tuple[int, int]:
    """Streaming watermark (last applied delta seq, topo_generation) of
    the newest loadable generation, reading only the two scalars (npz
    members load lazily — this never touches the state arrays).
    Returns (-1, 0) — the nominal graph — when there is no checkpoint
    or it predates the journal."""
    for path in _candidates(directory):
        try:
            with np.load(path) as data:
                seq = (int(data["__stream_seq__"])
                       if "__stream_seq__" in data.files else -1)
                gen = (int(data["__topo_generation__"])
                       if "__topo_generation__" in data.files else 0)
                return seq, gen
        except _READ_ERRORS:
            continue  # load_checkpoint will fall back the same way
    return -1, 0


def checkpoint_exists(directory: str) -> bool:
    return bool(_candidates(directory))


def _load_carry_from(path: str, template_comm: Any, parts: List[int]):
    """One generation's comm-carry rows for `parts` (see
    load_checkpoint_carry)."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template_comm)
    rows = np.asarray(parts, np.int64)
    try:
        data = np.load(path)
    except _READ_ERRORS as exc:
        raise CheckpointCorrupt(
            f"cannot open checkpoint {path}: {exc!r}") from exc
    try:
        digests = None
        if _DIGEST_KEY in data.files:
            try:
                digests = json.loads(str(data[_DIGEST_KEY][()]))
            except (*_READ_ERRORS, ValueError) as exc:
                raise CheckpointCorrupt(
                    f"unreadable digest manifest in {path}: {exc!r}"
                ) from exc
        leaves = []
        for p, tmpl in paths:
            key = "comm/" + _path_str(p)
            bf16 = False
            if _BF16_TAG + key in data.files:
                key, bf16 = _BF16_TAG + key, True
            elif key not in data.files:
                raise KeyError(f"checkpoint {path} missing leaf {key}")
            try:
                arr = data[key]
            except _READ_ERRORS as exc:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: member {key!r} unreadable "
                    f"({exc!r})") from exc
            # digest covers the FULL stored array: per-partition keying
            # is row-sliced AFTER verification, so a torn row can never
            # slip through just because another rank owns it
            if digests is not None and key in digests \
                    and _crc(arr) != digests[key]:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: digest mismatch for {key!r}")
            if bf16:
                arr = arr.view(_BF16)
            if arr.ndim < 1 or arr.shape[0] <= int(rows.max(initial=0)):
                raise ValueError(
                    f"checkpoint leaf {key}: leading dim "
                    f"{arr.shape[0] if arr.ndim else 0} cannot cover "
                    f"partitions {parts}")
            if tuple(arr.shape[1:]) != tuple(np.shape(tmpl)[1:]):
                raise ValueError(
                    f"checkpoint leaf {key}: per-partition shape "
                    f"{arr.shape[1:]} != template {np.shape(tmpl)[1:]}")
            arr = arr[rows]
            tdt = np.asarray(tmpl).dtype
            if arr.dtype != tdt:
                arr = arr.astype(tdt)
            leaves.append(arr)
        epoch = (int(data["__epoch__"]) if "__epoch__" in data.files
                 else -1)
    finally:
        data.close()
    return jax.tree_util.tree_unflatten(treedef, leaves), epoch


def load_checkpoint_carry(directory: str, template_comm: Any,
                          parts: List[int]):
    """Per-partition carry keying: ANY rank can load ANY shard's comm
    carry from a full-state checkpoint. Returns (comm_tree, epoch)
    where each leaf holds only rows ``parts`` of the stored [P, ...]
    array (epoch -1 for a legacy pre-__epoch__ layout).

    Checkpoints always store the FULL carry (host_state's allgather),
    keyed ``comm/<tree path>`` with the leading axis being the
    partition axis — so elastic redistribution
    (resilience/elastic.py) needs no writer-side cooperation: a
    process that inherits partitions {2, 3} after a membership change
    slices its rows out of whatever generation survives, digests
    verified, with the same newest-first generation fallback as
    :func:`load_checkpoint`. `template_comm` supplies the tree
    structure, dtypes and per-partition trailing shapes (its own
    leading dim is ignored)."""
    cands = _candidates(directory)
    if not cands:
        raise FileNotFoundError(f"no checkpoint in {directory}")
    last_exc: Optional[CheckpointCorrupt] = None
    for path in cands:
        try:
            tree, epoch = _load_carry_from(path, template_comm,
                                           list(parts))
            if last_exc is not None:
                warnings.warn(
                    f"carry restored from previous good checkpoint "
                    f"generation {os.path.basename(path)}")
            return tree, epoch
        except CheckpointCorrupt as exc:
            last_exc = exc
            warnings.warn(
                f"checkpoint generation {os.path.basename(path)} failed "
                f"verification ({exc}); falling back")
    raise CheckpointCorrupt(
        f"every checkpoint generation in {directory} failed "
        f"verification; last error: {last_exc}")


def verify_checkpoint(path: str) -> int:
    """Template-free full verification of one generation: every stored
    member is decompressed and checked against the ``__digests__``
    manifest. Returns the stored epoch (-1 for a legacy pre-__epoch__
    file). Raises :class:`CheckpointCorrupt` on any open/read/digest
    failure — including a missing manifest, since an unverifiable
    checkpoint is exactly what the soak invariants exist to reject.
    This is the soak harness's invariant 1 (resilience/soak.py); the
    trainer's load path stays on the template-driven
    :func:`load_checkpoint`."""
    try:
        data = np.load(path)
    except _READ_ERRORS as exc:
        raise CheckpointCorrupt(
            f"cannot open checkpoint {path}: {exc!r}") from exc
    try:
        if _DIGEST_KEY not in data.files:
            raise CheckpointCorrupt(
                f"checkpoint {path} has no digest manifest")
        try:
            digests = json.loads(str(data[_DIGEST_KEY][()]))
        except (*_READ_ERRORS, ValueError) as exc:
            raise CheckpointCorrupt(
                f"unreadable digest manifest in {path}: {exc!r}") from exc
        epoch = -1
        for key in data.files:
            if key == _DIGEST_KEY:
                continue
            try:
                arr = data[key]
            except _READ_ERRORS as exc:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: member {key!r} unreadable "
                    f"({exc!r})") from exc
            if key not in digests:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: member {key!r} missing from "
                    f"digest manifest")
            if _crc(arr) != digests[key]:
                raise CheckpointCorrupt(
                    f"checkpoint {path}: digest mismatch for {key!r}")
            if key == "__epoch__":
                epoch = int(arr)
    finally:
        data.close()
    return epoch


def peek_epoch(directory: str):
    """Epoch of the newest readable checkpoint in `directory` without a
    state template (npz members load lazily, so only the scalar is
    read). Returns None if no checkpoint exists; raises
    :class:`CheckpointCorrupt` when checkpoints exist but none is
    readable. Lets callers decide completed-vs-resume before paying
    full state construction (e.g. Trainer build at 114M edges,
    scripts/convergence_study.py)."""
    cands = _candidates(directory)
    if not cands:
        return None
    last_exc: Optional[CheckpointCorrupt] = None
    for path in cands:
        try:
            return _epoch_of(path, directory)
        except CheckpointCorrupt as exc:
            last_exc = exc
    raise CheckpointCorrupt(
        f"every checkpoint generation in {directory} is unreadable; "
        f"last error: {last_exc}")
