"""Checkpointing: pytrees <-> npz files.

The reference only saves the best model's state_dict at the end of
training (train.py:397) — and into a directory it never creates (latent
crash, SURVEY.md §2a). Here: directories are created, and full training
state (params + optimizer moments + norm state + pipelined comm buffers +
epoch) can be checkpointed and resumed, which the reference cannot do.

Format: one .npz per pytree, leaves keyed by their tree path; loading
restores into the structure of a caller-provided template pytree (shapes
and paths must match).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import numpy as np


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


_BF16 = np.dtype(jax.numpy.bfloat16.dtype)
# np.savez round-trips ml_dtypes.bfloat16 as raw void ('|V2'); store such
# leaves as a uint16 view under a tagged key instead
_BF16_TAG = "__bf16__/"


def save_pytree(path: str, tree: Any, extra: dict = None) -> None:
    """`extra` adds raw scalar/array entries (e.g. the checkpoint
    epoch) to the npz; load_pytree ignores them (it reads only the
    template's paths)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = dict(extra or {})
    for p, v in leaves:
        arr = np.asarray(v)
        key = _path_str(p)
        if arr.dtype == _BF16:
            arrays[_BF16_TAG + key] = arr.view(np.uint16)
        else:
            arrays[key] = arr
    # temp + atomic rename: an interrupted save (disk full, SIGTERM,
    # crash-handler save racing a second failure) must never destroy
    # the previous good checkpoint at `path`. The pid in the temp name
    # keeps multi-host SPMD processes — which all save the same state
    # to the same shared-filesystem path — from renaming each other's
    # half-written temp away (observed as FileNotFoundError on rank 1).
    # (np.savez appends ".npz" unless the name already ends with it)
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    try:
        np.savez_compressed(tmp, **arrays)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def load_pytree(path: str, template: Any, *, with_extras: bool = False):
    """Load arrays saved by save_pytree into template's structure.

    With with_extras=True returns (tree, extras) where extras holds the
    non-leaf keys (the `extra=` dict passed to save_pytree), so callers
    needing both never reopen the archive."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    leaf_keys = set()
    extras = {}
    with np.load(path) as data:
        for p, tmpl in paths:
            key = _path_str(p)
            if _BF16_TAG + key in data:
                arr = data[_BF16_TAG + key].view(_BF16)
                leaf_keys.add(_BF16_TAG + key)
            elif key in data:
                arr = data[key]
                leaf_keys.add(key)
            else:
                raise KeyError(f"checkpoint {path} missing leaf {key}")
            if tuple(arr.shape) != tuple(np.shape(tmpl)):
                raise ValueError(
                    f"checkpoint leaf {key}: shape {arr.shape} != template "
                    f"{np.shape(tmpl)}"
                )
            tdt = np.asarray(tmpl).dtype
            if arr.dtype != tdt:
                # e.g. resuming an f32-run checkpoint under --dtype
                # bfloat16: convert to the template's dtype so the restored
                # state matches the step's compiled avals
                arr = arr.astype(tdt)
            leaves.append(arr)
        if with_extras:
            for key in data.files:
                if key not in leaf_keys:
                    extras[key] = data[key]
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    return (tree, extras) if with_extras else tree


def save_checkpoint(directory: str, state: Dict[str, Any], epoch: int) -> None:
    """Save full training state for resume.

    The epoch rides INSIDE state.npz (one atomic os.replace), so a
    crash between writes can never pair a new state with an old epoch
    number — which would double-step the optimizer on resume."""
    os.makedirs(directory, exist_ok=True)
    _sweep_stale_tmps(directory)
    save_pytree(os.path.join(directory, "state.npz"), state,
                extra={"__epoch__": np.asarray(epoch, np.int64)})


def _sweep_stale_tmps(directory: str, min_age_s: float = 3600.0) -> None:
    """Remove orphaned pid-named *.tmp.npz left by a hard kill
    mid-save. Age-gated so a live peer process's in-flight temp (the
    multi-host concurrent-save case the pid naming exists for) is never
    touched."""
    import glob
    import time

    now = time.time()
    for tmp in glob.glob(os.path.join(directory, "*.tmp.npz")):
        try:
            if now - os.path.getmtime(tmp) > min_age_s:
                os.remove(tmp)
        except OSError:
            pass


def _legacy_epoch(directory: str) -> int:
    """Epoch of a pre-__epoch__ checkpoint layout (epoch.txt alongside
    state.npz). Raises if unreadable — a silent default would let
    callers resume from the wrong epoch."""
    with open(os.path.join(directory, "epoch.txt")) as f:
        return int(f.read().strip())


def load_checkpoint(directory: str, template: Dict[str, Any]):
    """Returns (state, next_epoch) restored from save_checkpoint."""
    state, extras = load_pytree(os.path.join(directory, "state.npz"),
                                template, with_extras=True)
    if "__epoch__" in extras:
        epoch = int(extras["__epoch__"])
    else:
        epoch = _legacy_epoch(directory)
    return state, epoch


def checkpoint_exists(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, "state.npz"))


def peek_epoch(directory: str):
    """Epoch of the checkpoint in `directory` without a state template
    (npz members load lazily, so only the scalar is read). Returns None
    if no checkpoint exists. Lets callers decide completed-vs-resume
    before paying full state construction (e.g. Trainer build at 114M
    edges, scripts/convergence_study.py)."""
    if not checkpoint_exists(directory):
        return None
    with np.load(os.path.join(directory, "state.npz")) as data:
        if "__epoch__" in data.files:
            return int(data["__epoch__"])
    return _legacy_epoch(directory)
