"""Timing utilities.

CommTimer mirrors the reference's helper/timer/comm_timer.py API (spans
keyed 'forward_{layer}'/'backward_{layer}', duplicate keys raise,
`tot_time()` summed per epoch, `clear()` between epochs) so tooling built
against the reference's log discipline keeps working. In the SPMD design
the per-layer comm is inside one jitted step, so these spans wrap
host-blocking regions (step dispatch, eval) rather than gloo waits; the
per-collective breakdown comes from `Trainer.measure_comm()` (standalone
timing of the exchange/reduce collectives) and `jax.profiler` traces
(--profile-dir).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict


class CommTimer:
    def __init__(self):
        self._durs: Dict[str, float] = {}

    @contextmanager
    def timer(self, key: str):
        if key in self._durs:
            raise RuntimeError(f"duplicate timer key: {key}")
        t0 = time.perf_counter()
        yield
        self._durs[key] = time.perf_counter() - t0

    def tot_time(self) -> float:
        return sum(self._durs.values())

    def durations(self) -> Dict[str, float]:
        return dict(self._durs)

    def clear(self) -> None:
        self._durs.clear()
