"""Timing utilities — the reference-parity shim over PhaseTimer.

CommTimer mirrors the reference's helper/timer/comm_timer.py API (spans
keyed 'forward_{layer}'/'backward_{layer}', duplicate keys raise,
`tot_time()` summed per epoch, `clear()` between epochs) so tooling
built against the reference's log discipline keeps working. It is a
thin shim over `pipegcn_tpu.obs.trace.PhaseTimer`, which generalizes
it: exception-safe recording (a span that raises still lands its
duration — the original lost it), re-entrant keys that accumulate, and
free nesting. In the SPMD design the per-layer comm is inside one
jitted step, so these spans wrap host-blocking regions (step dispatch,
eval) rather than gloo waits; the per-collective breakdown comes from
`Trainer.measure_comm()` (standalone timing of the exchange/reduce
collectives) and `jax.profiler` traces (--profile-dir).
"""

from __future__ import annotations

from contextlib import contextmanager

from ..obs.trace import PhaseTimer

__all__ = ["CommTimer", "PhaseTimer"]


class CommTimer(PhaseTimer):
    @contextmanager
    def timer(self, key: str):
        # reference comm_timer.py:14-15 semantics: one span per key per
        # epoch; PhaseTimer.phase records in a finally, so an exception
        # inside the span still lands the duration before propagating
        if key in self._durs:
            raise RuntimeError(f"duplicate timer key: {key}")
        with self.phase(key):
            yield
